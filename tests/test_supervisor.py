"""Actor supervisor tests: restart-on-crash, chaos liveness, budgets.

SURVEY.md §6 failure detection: "actor supervisor that restarts dead env
workers" + "a chaos flag that kills random actors in tests to prove
liveness".
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torched_impala_tpu.envs.fake import CrashingEnv, FakeDiscreteEnv
from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
from torched_impala_tpu.runtime import (
    Actor,
    ActorSupervisor,
    Learner,
    LearnerConfig,
)
from torched_impala_tpu.runtime.loop import train


def _small_agent(num_actions=3, obs=(6,)):
    return Agent(
        ImpalaNet(num_actions=num_actions, torso=MLPTorso(hidden_sizes=(16,)))
    )


class TestSupervisorUnit:
    def test_restarts_crashed_actor_and_unrolls_keep_flowing(self):
        agent = _small_agent()
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-3),
            config=LearnerConfig(batch_size=2, unroll_length=4),
            example_obs=np.zeros((6,), np.float32),
            rng=jax.random.key(0),
        )
        stop = threading.Event()
        spawned = []

        def make_actor(slot):
            spawned.append(slot)
            env = CrashingEnv(
                FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=slot),
                crash_after=10,  # ~2 unrolls then crash
            )
            return Actor(
                actor_id=slot,
                env=env,
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=4,
                seed=slot,
            )

        sup = ActorSupervisor(
            make_actor=make_actor,
            num_actors=2,
            stop_event=stop,
            check_interval=0.05,
            backoff_base=0.01,
        )
        sup.start()
        learner.start()
        try:
            for _ in range(4):
                logs = learner.step_once(timeout=60)
                assert np.isfinite(float(logs["total_loss"]))
        finally:
            stop.set()
            learner.stop()
            sup.join()
        # 4 learner steps x B=2 = 8 unrolls consumed; each actor crashes
        # every ~2 unrolls, so restarts must have happened.
        assert sup.restarts >= 1
        assert len(spawned) == 2 + sup.restarts

    def test_budget_exhaustion_reports_unrecoverable(self):
        agent = _small_agent()
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-3),
            config=LearnerConfig(batch_size=1, unroll_length=4),
            example_obs=np.zeros((6,), np.float32),
            rng=jax.random.key(0),
        )
        stop = threading.Event()

        def make_actor(slot):
            env = CrashingEnv(
                FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=slot),
                crash_after=1,  # dies on the very first step, every time
            )
            return Actor(
                actor_id=slot,
                env=env,
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=4,
                seed=slot,
            )

        sup = ActorSupervisor(
            make_actor=make_actor,
            num_actors=1,
            stop_event=stop,
            check_interval=0.02,
            max_restarts_per_actor=2,
            backoff_base=0.01,
        )
        sup.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.alive_count() == 0 and not sup.can_recover():
                break
            time.sleep(0.05)
        stop.set()
        sup.join()
        assert sup.restarts == 2
        assert not sup.can_recover()
        assert "chaos" in repr(sup.errors()[0])

    def test_spawn_failure_does_not_kill_monitor(self):
        # make_actor raising during a restart must consume the restart and
        # leave the monitor alive to retry — not hang training forever.
        agent = _small_agent()
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-3),
            config=LearnerConfig(batch_size=1, unroll_length=4),
            example_obs=np.zeros((6,), np.float32),
            rng=jax.random.key(0),
        )
        stop = threading.Event()
        calls = [0]

        def make_actor(slot):
            calls[0] += 1
            if calls[0] == 2:  # the first restart's respawn blows up
                raise RuntimeError("env re-init failed")
            crash_after = 6 if calls[0] < 3 else 10_000
            env = CrashingEnv(
                FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=slot),
                crash_after=crash_after,
            )
            return Actor(
                actor_id=slot,
                env=env,
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=4,
                seed=slot,
            )

        sup = ActorSupervisor(
            make_actor=make_actor,
            num_actors=1,
            stop_event=stop,
            check_interval=0.02,
            backoff_base=0.01,
        )
        sup.start()
        learner.start()
        try:
            # Needs the third spawn (post-failure retry) to produce unrolls.
            logs = learner.step_once(timeout=60)
            assert np.isfinite(float(logs["total_loss"]))
        finally:
            stop.set()
            learner.stop()
            sup.join()
        assert calls[0] >= 3
        assert any("re-init" in repr(e) for e in sup.errors())

    def test_clean_exit_is_not_restarted(self):
        # An actor that finishes max_unrolls exits without error; the
        # supervisor must leave it alone.
        agent = _small_agent()
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-3),
            config=LearnerConfig(batch_size=4, unroll_length=4),
            example_obs=np.zeros((6,), np.float32),
            rng=jax.random.key(0),
        )
        stop = threading.Event()

        class OneShotActor(Actor):
            def run(self, stop_event, max_unrolls=None):
                return super().run(stop_event, max_unrolls=1)

        def make_actor(slot):
            return OneShotActor(
                actor_id=slot,
                env=FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=slot),
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=4,
                seed=slot,
            )

        sup = ActorSupervisor(
            make_actor=make_actor,
            num_actors=2,
            stop_event=stop,
            check_interval=0.02,
        )
        sup.start()
        deadline = time.monotonic() + 20
        while sup.alive_count() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        time.sleep(0.2)  # give the monitor a chance to (wrongly) restart
        stop.set()
        sup.join()
        assert sup.restarts == 0
        assert not sup.can_recover()  # dead without error = clean


class TestChaosTraining:
    def test_training_survives_crashing_envs(self):
        # End-to-end liveness: envs crash regularly, the run still reaches
        # its step budget and reports the restarts it needed.
        agent = _small_agent()

        def env_factory(seed):
            return CrashingEnv(
                FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=seed),
                crash_after=25,
            )

        result = train(
            agent=agent,
            env_factory=env_factory,
            example_obs=np.zeros((6,), np.float32),
            num_actors=2,
            learner_config=LearnerConfig(batch_size=2, unroll_length=5),
            optimizer=optax.sgd(1e-3),
            total_steps=8,
            log_every=4,
        )
        assert result.learner.num_steps == 8
        assert result.actor_restarts >= 1

    def test_unrecoverable_fleet_fails_loudly(self):
        agent = _small_agent()

        def env_factory(seed):
            return CrashingEnv(
                FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=seed),
                crash_after=1,
            )

        with pytest.raises(RuntimeError, match="unrecoverable"):
            train(
                agent=agent,
                env_factory=env_factory,
                example_obs=np.zeros((6,), np.float32),
                num_actors=2,
                learner_config=LearnerConfig(batch_size=2, unroll_length=5),
                optimizer=optax.sgd(1e-3),
                total_steps=4,
                max_actor_restarts=1,
            )

    def test_chaos_with_fused_dispatch_stays_live(self):
        """Fault injection composed with fused dispatch: actors crashing
        mid-run must not stall superbatch assembly — the supervisor
        restarts them and the learner still completes its K-step
        dispatches."""
        result = train(
            agent=_small_agent(),
            env_factory=lambda seed, env_index=None: CrashingEnv(
                FakeDiscreteEnv(obs_shape=(6,), num_actions=3, seed=seed),
                crash_after=25,
            ),
            example_obs=np.zeros((6,), np.float32),
            num_actors=2,
            learner_config=LearnerConfig(
                batch_size=2, unroll_length=4, steps_per_dispatch=2
            ),
            optimizer=optax.sgd(1e-3),
            total_steps=6,
            seed=0,
            log_every=1,
            max_actor_restarts=50,
        )
        assert result.learner.num_steps == 6  # 3 dispatches x K=2
        assert np.isfinite(result.final_logs.get("total_loss", np.nan))


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
