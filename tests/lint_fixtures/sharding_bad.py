"""Seeded sharding-contract violations (every sharding/* rule fires).

Parsed by tools/lint/sharding.py, never imported.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def adhoc_spec(x, mesh):
    # ad-hoc-spec (constructed outside spec_layout.py) AND
    # undeclared-axis ('batch') AND spec-table-mismatch (no table
    # entry puts an axis there).
    spec = P(None, "batch")
    return jax.device_put(x, NamedSharding(mesh, spec))


def bad_collective(x):
    # undeclared-axis: collective axis argument.
    return jax.lax.psum(x, "sequence")


def bad_mesh(devs):
    # undeclared-axis: typo'd Mesh axis tuple.
    return Mesh(devs, ("data", "modle"))


def takes_axis(q, *, axis_name):
    # axis parameter: callers' string bindings are validated.
    return jax.lax.all_gather(q, axis_name)


def forwards_axis(q, ring_axis):
    # 1-hop flow: ring_axis is an axis param because it reaches
    # takes_axis(axis_name=...).
    return takes_axis(q, axis_name=ring_axis)


def bad_caller(q):
    # undeclared-axis via the call graph, two hops from the collective.
    return forwards_axis(q, "sequenze")


def bad_arity(mesh):
    # spec-arity-mismatch: 3-dim spec on a rank-2 array (also ad-hoc).
    x = jnp.zeros((4, 8))
    return jax.device_put(x, NamedSharding(mesh, P(None, None, "data")))
