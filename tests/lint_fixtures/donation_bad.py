"""Seeded interprocedural donation-liveness violation.

`Learner.train` forwards its `params` into a donate_argnums position,
so callers' buffers die across `train()` — `run` reads `p` after.
Parsed by tools/lint/donation.py, never imported.
"""

import jax


class Learner:
    def __init__(self):
        self._step = jax.jit(self._impl, donate_argnums=(0, 1))

    def _impl(self, params, opt, batch):
        return params, opt

    def train(self, params, opt, batch):
        # params/opt are donated here; train() transfers the
        # obligation to its callers (donates = {0, 1}).
        params, opt = self._step(params, opt, batch)
        return params, opt

    def run(self, p, o, batch):
        out = self.train(p, o, batch)
        return out, p  # donated p read after the call: finding
