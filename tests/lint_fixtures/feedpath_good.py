"""GOOD fixture for sharding/feed-path-placement: a runtime/ module
whose batch shardings resolve through SpecLayout's batch-placement
builders — no NamedSharding construction on the feed path."""

from torched_impala_tpu.parallel import multihost, spec_layout


def put_batch(mesh, arrays, fused):
    shardings = spec_layout.feed_shardings(mesh, superbatch=fused)
    return multihost.place_batch(shardings, arrays)
