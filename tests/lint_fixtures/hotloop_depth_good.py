"""Transitive-hot-loop clean: the helper one call below the annotated
loop stays async end to end. Silent at any --hot-loop-depth.
"""


class Server:
    def _serve_loop(self):  # lint: hot-loop
        while True:
            self.step_once()

    def step_once(self):
        logits = self._infer()
        self._out_ring.push(logits)  # stays on device, no sync
        return logits
