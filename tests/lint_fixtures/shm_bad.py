"""Seeded shm-lifecycle violations (impala-lint fixture — parsed, never
imported). One positive per rule; tests/test_lint.py asserts each."""

import numpy as np
from multiprocessing import shared_memory


class LeakyOwner:
    """no-close AND no-unlink: owns a segment, tears nothing down."""

    def __init__(self, size: int):
        self._shm = shared_memory.SharedMemory(create=True, size=size)
        self.lane = np.ndarray((size,), np.uint8, buffer=self._shm.buf)


class CloseButNoUnlink:
    """no-unlink: closes its mapping but leaves the name in /dev/shm."""

    def __init__(self, size: int):
        self._shm = shared_memory.SharedMemory(create=True, size=size)

    def close(self):
        self._shm.close()


def attach_and_maybe_leak(name: str):
    """local-no-finally: an exception between attach and close leaks
    the mapping."""
    shm = shared_memory.SharedMemory(name=name)
    view = np.ndarray((8,), np.uint8, buffer=shm.buf)
    total = int(view.sum())  # may raise on a truncated segment
    shm.close()
    return total
