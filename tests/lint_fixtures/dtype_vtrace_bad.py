"""Seeded violation: ANY half-precision dtype token inside a V-trace /
PopArt module (the file name carries the scope) is a finding — these
modules are f32-only by policy. Parsed, never imported.
"""

import jax.numpy as jnp


def backward_scan(deltas):
    acc = jnp.zeros_like(deltas, dtype=jnp.bfloat16)
    return acc
