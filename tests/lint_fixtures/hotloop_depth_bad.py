"""Seeded transitive hot-loop violation: the host sync hides ONE call
below the annotated loop — invisible at --hot-loop-depth 0, caught at
depth 1. Parsed, never imported.
"""


class Server:
    def _serve_loop(self):  # lint: hot-loop
        while True:
            self.step_once()

    def step_once(self):
        logits = self._infer()
        return logits.block_until_ready()  # 1 call deep from the loop
