"""dtype-policy clean: stats stay f32, the half cast lives inside a
jit root. The dtype checker must stay silent.
"""

import jax
import jax.numpy as jnp


@jax.jit
def compute(x):
    # half cast INSIDE the jit root: the explicit, compiled boundary.
    h = x.astype(jnp.bfloat16)
    return (h @ h.T).astype(jnp.float32)


def update_stats(x, mu, nu):
    mu = x.mean(dtype=jnp.float32)
    nu = jnp.zeros((4,), dtype=jnp.float32)
    return mu, nu
