"""Clean shm-lifecycle patterns (impala-lint fixture — parsed, never
imported): the negative case per rule. Must produce ZERO findings."""

import numpy as np
from multiprocessing import shared_memory


class TidyOwner:
    """Owner: close + unlink on teardown, __del__ safety net."""

    def __init__(self, size: int):
        self._shm = shared_memory.SharedMemory(create=True, size=size)
        self.lane = np.ndarray((size,), np.uint8, buffer=self._shm.buf)
        self._closed = False

    def close(self):
        if self._closed:
            return
        self._closed = True
        del self.lane
        self._shm.close()
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class TidyAttacher:
    """Attach side: close only — the owner unlinks."""

    def __init__(self, name: str):
        self._shm = shared_memory.SharedMemory(name=name)

    def close(self):
        self._shm.close()


def attach_and_sum(name: str):
    """Local attach closed in a finally: every exit path unmaps."""
    shm = shared_memory.SharedMemory(name=name)
    try:
        view = np.ndarray((8,), np.uint8, buffer=shm.buf)
        return int(view.sum())
    finally:
        shm.close()
