"""Seeded dtype-policy violations (stats-not-f32 direct and via a
helper's return, cast-outside-jit-root). The module name carries no
popart/vtrace token so half-in-accumulator-module is exercised by
dtype_vtrace_bad.py instead. Parsed, never imported.
"""

import jax.numpy as jnp


def halved(x):
    # returns-half summary feeds the interprocedural stats rule; the
    # cast itself is also outside any jit root.
    return x.astype(jnp.bfloat16)


def update_stats(x, mu, nu):
    mu = halved(x)  # stats-not-f32 via 1-hop return flow
    nu = jnp.zeros((4,), dtype=jnp.bfloat16)  # stats-not-f32 direct
    return mu, nu
