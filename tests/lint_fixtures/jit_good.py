"""Clean jit-boundary patterns (impala-lint fixture — parsed, never
imported): the negative case per rule. Must produce ZERO findings."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def clean_step(x):
    # jnp stays on device; float() of a closure CONSTANT is static.
    scale = float(np.prod((2, 2)))
    jax.debug.print("x sum {s}", s=x.sum())  # the in-jit print
    return jnp.tanh(x) * scale


class Trainer:
    def __init__(self):
        self._step = jax.jit(self._impl, donate_argnums=(0,))

    def _impl(self, params, batch):
        return jax.tree.map(lambda p: p + batch.mean(), params)

    def train(self, params, batch):
        # Donated arg rebound from the result: dead afterwards, correct.
        params = self._step(params, batch)
        return params

    def consume(self, data):  # lint: hot-loop
        total = jnp.zeros(())
        for row in data:
            total = total + row.sum()  # stays on device
        # Deliberate sync, annotated where it happens:
        return total.item()  # lint: allow(jit-boundary)
