"""Seeded jit-boundary violations (impala-lint fixture — parsed, never
imported). One positive per rule; tests/test_lint.py asserts each."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def leaky_step(x):
    y = x * 2
    print("tracing", y)  # <- fires at trace time only
    z = np.asarray(y)  # <- host materialization inside jit
    return float(x.sum()) + z.mean()  # <- float() on a traced value


@functools.partial(jax.jit, static_argnums=(1,))
def sync_inside(x, n):
    jax.block_until_ready(x)  # <- host blocks inside jit
    return x.sum().item() + n  # <- .item() device->host


class Trainer:
    """jit root discovered through jax.jit(self._impl, ...) plus the
    self-call closure, and a donated arg read after the call."""

    def __init__(self):
        self._step = jax.jit(self._impl, donate_argnums=(0,))

    def _impl(self, params, batch):
        return self._loss(params, batch)

    def _loss(self, params, batch):
        del batch
        return jax.device_get(params)  # <- host sync in traced helper

    def train(self, params, batch):
        new_params = self._step(params, batch)  # donates params...
        stale = jnp.sum(params)  # <- ...then reads the donated buffer
        return new_params, stale

    def consume(self, data):  # lint: hot-loop
        total = 0.0
        for row in data:
            total += row.sum().item()  # <- sync inside a hot loop
        return total
