"""Clean telemetry-grammar patterns (impala-lint fixture — parsed,
never imported). Must produce ZERO findings."""

reg.counter("pool/restarts")  # noqa: F821
reg.gauge("queue/depth")  # noqa: F821
reg.timer("learner/train_step")  # noqa: F821
reg.span("learner/train_step")  # span == timer: same series, no fork  # noqa: F821
reg.counter("resilience/checkpoint_bytes")  # pinned sub-family  # noqa: F821
reg.counter("serving/request_total")  # pinned sub-family  # noqa: F821
reg.counter("replay/reuse_delivered")  # pinned sub-family (3d)  # noqa: F821
reg.gauge("replay/target_lag")  # pinned sub-family (3d)  # noqa: F821
reg.gauge("perf/mfu")  # bare family name passes 3e  # noqa: F821
reg.gauge("perf/membw_util")  # pinned sub-family (3e)  # noqa: F821
reg.counter("perf/fused_fallbacks")  # pinned sub-family (3e)  # noqa: F821
reg.counter("control/decision_total")  # pinned sub-family (3f)  # noqa: F821
reg.counter("control/revert_total")  # pinned sub-family (3f)  # noqa: F821
reg.gauge("control/objective_delta")  # pinned sub-family (3f)  # noqa: F821
reg.gauge("control/knob_value")  # pinned sub-family (3f)  # noqa: F821
rec.instant("control/decision", {"knob": "k"})  # bare family trace passes 3f  # noqa: F821
reg.counter("serving/fleet_rollout_total")  # pinned sub-family (3g)  # noqa: F821
reg.gauge("serving/fleet_active")  # pinned sub-family (3g)  # noqa: F821
reg.counter("serving/route_retry_total")  # pinned sub-family (3g)  # noqa: F821
reg.histogram("serving/route_latency_ms")  # pinned sub-family (3g)  # noqa: F821
reg.gauge("alerts/firing_pool_step_p99")  # pinned sub-family (3h)  # noqa: F821
reg.gauge("alerts/burn_rate_pool_step_p99")  # pinned sub-family (3h)  # noqa: F821
reg.gauge("health/clip_rho_frac")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/entropy_mean")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/kl_behaviour_learner")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/ev_value")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/grad_spike_ratio")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/update_ratio_torso")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/popart_mu_drift")  # pinned sub-family (3j)  # noqa: F821
reg.gauge("health/staleness_clip_corr")  # pinned sub-family (3j)  # noqa: F821
key = "telemetry/pool/restarts"
agg_key = "telemetry/proc0w1/pool/worker_step_ms_p50"  # aggregated form (3i)
agg_key_mh = "telemetry/proc12w3/pool/worker_step_ms_p50"  # multi-host form: h is a real process index (ISSUE 18)
rec.instant("telemetry/alert", {"slo": "pool_step_p99"})  # trace name, not a metric key  # noqa: F821
rec.instant("ring/commit", {"lid": "a0u0"})  # noqa: F821
rec.complete("serving/request", 0, 1)  # pinned trace set  # noqa: F821
rec.instant("serving/rollout", {"phase": "drain"})  # pinned trace set (3g additions)  # noqa: F821
rec.instant("serving/failover", {"replica": "r0"})  # pinned trace set (3g additions)  # noqa: F821
