"""Seeded telemetry-grammar violations (impala-lint fixture — parsed,
never imported). One positive per rule; tests/test_lint.py asserts
each. Mirrors the legacy check_metric_names fixture cases exactly."""

reg.counter("NoSlash")  # name-grammar  # noqa: F821
reg.gauge("pool/depth")  # noqa: F821
reg.timer("pool/depth")  # type-fork with the gauge above  # noqa: F821
x = "telemetry/bad key here"  # prose: must NOT flag
y = "telemetry/bad/Key"  # malformed literal: not flagged (charset)
z = "telemetry/ok/key"
bad_literal = "telemetry/0bad"  # literal-key (leading digit component)
reg.counter("resilience/orphan_series")  # subfamily-prefix  # noqa: F821
reg.counter("serving/orphan_series")  # subfamily-prefix  # noqa: F821
reg.counter("replay/orphan_series")  # subfamily-prefix (rule 3d)  # noqa: F821
reg.counter("perf/orphan_series")  # subfamily-prefix (rule 3e)  # noqa: F821
reg.gauge("perf/mfuzzy")  # subfamily-prefix (3e: prefix, not substring)  # noqa: F821
reg.counter("control/orphan_series")  # subfamily-prefix (rule 3f)  # noqa: F821
reg.gauge("control/decisions_made")  # subfamily-prefix (3f: prefix, not substring)  # noqa: F821
reg.counter("serving/fleetsize")  # subfamily-prefix (3g: fleet_ prefix, not substring)  # noqa: F821
reg.gauge("serving/routesplit")  # subfamily-prefix (3g: route_ prefix, not substring)  # noqa: F821
reg.gauge("alerts/burning")  # subfamily-prefix (3h: burn_ prefix, not substring)  # noqa: F821
reg.counter("alerts/orphan_series")  # subfamily-prefix (rule 3h)  # noqa: F821
reg.counter("health/orphan_series")  # subfamily-prefix (rule 3j)  # noqa: F821
reg.gauge("health/clipping")  # subfamily-prefix (3j: clip_ prefix, not substring)  # noqa: F821
bad_agg = "telemetry/proc0wx/pool/step_ms"  # agg-prefix (malformed label)  # noqa: F821
bad_agg2 = "telemetry/proc0w1/0bad/step"  # agg-prefix (bad remainder)  # noqa: F821
bad_agg3 = "telemetry/proc1x2w0/pool/step_ms"  # agg-prefix (junk inside a multi-host label)  # noqa: F821
rec.instant("Bad.Trace")  # trace-grammar  # noqa: F821
rec.complete("serving/rogue_event", 0, 1)  # trace-closed-set  # noqa: F821
rec.instant("serving/rollback")  # trace-closed-set (rollout is pinned, rollback is not)  # noqa: F821
