"""Clean thread-safety patterns (impala-lint fixture — parsed, never
imported): every rule's negative case. Must produce ZERO findings."""

import collections
import queue
import threading


class GuardedCounter:
    """Writes under one declared lock from both thread groups."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._lock:
                self.count += 1

    def reset(self):
        with self._lock:
            self.count = 0


class AnnotatedHelpers:
    """Caller-holds-lock methods (guarded-by on the def), a declared
    gil-atomic flag, thread-safe containers bound once in __init__, and
    correctly ORDERED nested locks (one direction only — no cycle)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
        self._q = queue.Queue()
        self._pending = collections.deque()
        self._stop = threading.Event()
        # Single-writer atomic rebind: background sets, foreground reads.
        self.error = None  # lint: guarded-by(gil)

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        try:
            while not self._stop.is_set():
                with self._lock:
                    self._mutate_locked()
                    with self._aux:
                        pass
        except BaseException as e:
            self.error = e

    def _mutate_locked(self):  # lint: guarded-by(_lock)
        self.value = 1

    def submit(self, item):
        self._q.put(item)
        with self._lock:
            self._mutate_locked()
