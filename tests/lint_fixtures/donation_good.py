"""Donation-clean: every donated argument is rebound from the result
(the idiomatic `params, opt = train(params, opt, ...)` cycle) or never
read again. The donation checker must stay silent.
"""

import jax


class Learner:
    def __init__(self):
        self._step = jax.jit(self._impl, donate_argnums=(0, 1))

    def _impl(self, params, opt, batch):
        return params, opt

    def train(self, params, opt, batch):
        params, opt = self._step(params, opt, batch)
        return params, opt

    def run(self, p, o, batch):
        p, o = self.train(p, o, batch)
        return p, o  # rebound from the result: dead buffers, correct

    def last_use(self, p, o, batch):
        return self.train(p, o, batch)  # no read after: correct
