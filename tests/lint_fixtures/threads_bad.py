"""Seeded thread-safety violations (impala-lint fixture — parsed, never
imported). One positive per rule; tests/test_lint.py asserts each."""

import threading


class UnguardedCounter:
    """unguarded-attr: background thread writes `count`, foreground
    reads it, no lock held anywhere."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            self.count += 1  # <- unguarded cross-thread write

    def read(self):
        return self.count


class MixedLocks:
    """mixed-locks: `state` written under lock_a in one method and
    lock_b in another — two locks exclude nobody."""

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self.state = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock_a:
            self.state += 1

    def poke(self):
        with self._lock_b:
            self.state = 0


class BadAnnotation:
    """unknown-lock: guarded-by names a lock the class never declares."""

    def __init__(self):
        self._lock = threading.Lock()
        self.flag = False  # lint: guarded-by(_missing_lock)

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self.flag = True

    def read(self):
        return self.flag


class LockCycle:
    """lock-cycle: a() takes lock1 then lock2, b() takes lock2 then
    lock1 — the classic ABBA deadlock schedule."""

    def __init__(self):
        self._lock1 = threading.Lock()
        self._lock2 = threading.Lock()

    def a(self):
        with self._lock1:
            with self._lock2:
                pass

    def b(self):
        with self._lock2:
            with self._lock1:
                pass


class IndirectCycle:
    """lock-cycle through a call: outer() holds lock_x and calls
    helper(), which takes lock_y; rev() nests them the other way."""

    def __init__(self):
        self._lock_x = threading.Lock()
        self._lock_y = threading.Lock()

    def outer(self):
        with self._lock_x:
            self.helper()

    def helper(self):
        with self._lock_y:
            pass

    def rev(self):
        with self._lock_y:
            with self._lock_x:
                pass
