"""Contract-clean sharding: axes declared, specs from SpecLayout
builders, arity consistent. The sharding checker must stay silent.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from torched_impala_tpu.parallel import spec_layout


def declared_collective(x):
    return jax.lax.psum(x, "data")


def declared_mesh(devs):
    return Mesh(devs, ("data", "model"))


def table_spec(x, mesh):
    return jax.device_put(
        x, NamedSharding(mesh, spec_layout.batch_spec())
    )


def takes_axis(q, *, axis_name):
    return jax.lax.all_gather(q, axis_name)


def good_caller(q):
    return takes_axis(q, axis_name="seq")


def good_arity(mesh):
    x = jnp.zeros((4, 8, 3))
    return jax.device_put(
        x, NamedSharding(mesh, spec_layout.tensor_spec("batch_major"))
    )
