"""BAD fixture for sharding/feed-path-placement: a runtime/ module
constructing feed-path shardings ad hoc instead of resolving them
through SpecLayout's BATCH_PLACEMENT builders. test_lint scans this
body under a torched_impala_tpu/runtime/ rel path."""

import jax
from jax.sharding import NamedSharding

from torched_impala_tpu.parallel import spec_layout


def put_batch(mesh, arrays):
    # ad-hoc per-call sharding on the feed path: the placement no
    # longer resolves through the canonical table
    sh = NamedSharding(mesh, spec_layout.batch_spec())
    return jax.device_put(arrays, sh)
