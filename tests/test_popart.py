"""PopArt tests: EMA oracle, output preservation, loss consistency, e2e.

Mirrors the build test plan (SURVEY.md §5): pure-function math against numpy
oracles, then an integration step through the real Learner with a multi-task
fake env batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torched_impala_tpu.ops import popart
from torched_impala_tpu.ops.losses import ImpalaLossConfig, impala_loss
from torched_impala_tpu.ops.popart import PopArtConfig, PopArtState


def _rand_inputs(rng, T=7, B=5, A=4):
    return dict(
        target_logits=jnp.asarray(rng.normal(size=(T, B, A)), jnp.float32),
        behaviour_logits=jnp.asarray(
            rng.normal(size=(T, B, A)), jnp.float32
        ),
        actions=jnp.asarray(rng.integers(0, A, size=(T, B)), jnp.int32),
        rewards=jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
        discounts=jnp.asarray(
            0.99 * (rng.uniform(size=(T, B)) > 0.1), jnp.float32
        ),
    )


class TestUpdate:
    def test_matches_numpy_ema_oracle(self):
        rng = np.random.default_rng(0)
        cfg = PopArtConfig(num_values=3, step_size=0.1)
        state = popart.init(3)
        T, B = 6, 8
        targets = rng.normal(size=(T, B)).astype(np.float32) * 5 + 2
        tasks = rng.integers(0, 3, size=(B,)).astype(np.int32)
        mask = (rng.uniform(size=(T, B)) > 0.2).astype(np.float32)

        new = popart.update(
            state, cfg, jnp.asarray(targets), jnp.asarray(tasks),
            jnp.asarray(mask),
        )

        mu, nu = np.zeros(3), np.ones(3)
        for k in range(3):
            sel = tasks == k
            m = mask[:, sel]
            if m.sum() == 0:
                continue
            t = targets[:, sel]
            mu[k] += 0.1 * ((t * m).sum() / m.sum() - mu[k])
            nu[k] += 0.1 * ((t**2 * m).sum() / m.sum() - nu[k])
        np.testing.assert_allclose(np.asarray(new.mu), mu, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(new.nu), nu, rtol=1e-5)

    def test_absent_task_stats_unchanged(self):
        cfg = PopArtConfig(num_values=4, step_size=0.5)
        state = PopArtState(
            mu=jnp.arange(4.0), nu=jnp.arange(4.0) ** 2 + 1.0
        )
        targets = jnp.ones((3, 2)) * 100.0
        tasks = jnp.asarray([1, 1], jnp.int32)  # only task 1 present
        new = popart.update(state, cfg, targets, tasks, jnp.ones((3, 2)))
        for k in (0, 2, 3):
            assert float(new.mu[k]) == float(state.mu[k])
            assert float(new.nu[k]) == float(state.nu[k])
        assert float(new.mu[1]) != float(state.mu[1])

    def test_converges_to_target_moments(self):
        # Repeated updates with constant targets drive sigma/mu to them.
        cfg = PopArtConfig(num_values=1, step_size=0.3)
        state = popart.init(1)
        rng = np.random.default_rng(1)
        targets_all = rng.normal(loc=10.0, scale=4.0, size=(100, 8, 16))
        tasks = jnp.zeros((16,), jnp.int32)
        mask = jnp.ones((8, 16))
        for i in range(100):
            state = popart.update(
                state, cfg, jnp.asarray(targets_all[i], jnp.float32),
                tasks, mask,
            )
        assert abs(float(state.mu[0]) - 10.0) < 0.5
        assert abs(float(popart.sigma(state, cfg)[0]) - 4.0) < 0.5


class TestOutputPreservation:
    def test_unnormalized_outputs_exact(self):
        rng = np.random.default_rng(2)
        cfg = PopArtConfig(num_values=3)
        old = PopArtState(
            mu=jnp.asarray(rng.normal(size=3), jnp.float32),
            nu=jnp.asarray(rng.uniform(2, 9, size=3), jnp.float32),
        )
        new = PopArtState(
            mu=jnp.asarray(rng.normal(size=3), jnp.float32),
            nu=jnp.asarray(rng.uniform(2, 9, size=3), jnp.float32),
        )
        F = 16
        kernel = jnp.asarray(rng.normal(size=(F, 3)), jnp.float32)
        bias = jnp.asarray(rng.normal(size=(3,)), jnp.float32)
        feats = jnp.asarray(rng.normal(size=(11, F)), jnp.float32)
        tasks = jnp.asarray(rng.integers(0, 3, size=(11,)), jnp.int32)

        k2, b2 = popart.rescale_head(kernel, bias, old, new, cfg)
        n_old = feats @ kernel + bias
        n_new = feats @ k2 + b2
        un_old = popart.unnormalize(
            old, cfg, jnp.take_along_axis(n_old, tasks[:, None], 1)[:, 0],
            tasks,
        )
        un_new = popart.unnormalize(
            new, cfg, jnp.take_along_axis(n_new, tasks[:, None], 1)[:, 0],
            tasks,
        )
        np.testing.assert_allclose(
            np.asarray(un_old), np.asarray(un_new), rtol=1e-5, atol=1e-5
        )

    def test_rescale_params_tree_roundtrip(self):
        # rescale_params edits only value_head, leaves the rest alone.
        rng = np.random.default_rng(3)
        cfg = PopArtConfig(num_values=2)
        old = popart.init(2)
        new = PopArtState(mu=jnp.asarray([1.0, -1.0]),
                          nu=jnp.asarray([5.0, 3.0]))
        params = {
            "params": {
                "value_head": {
                    "kernel": jnp.asarray(
                        rng.normal(size=(4, 2)), jnp.float32
                    ),
                    "bias": jnp.zeros((2,)),
                },
                "policy_head": {"kernel": jnp.ones((4, 3))},
            }
        }
        out = popart.rescale_params(params, old, new, cfg)
        assert out["params"]["policy_head"]["kernel"] is (
            params["params"]["policy_head"]["kernel"]
        )
        assert not np.allclose(
            np.asarray(out["params"]["value_head"]["kernel"]),
            np.asarray(params["params"]["value_head"]["kernel"]),
        )


class TestPopArtLoss:
    def test_identity_stats_matches_plain_impala_loss(self):
        # With mu=0 sigma=1 and step_size=0 the PopArt loss IS the IMPALA
        # loss (values are "normalized" by the identity).
        rng = np.random.default_rng(4)
        T, B = 7, 5
        inputs = _rand_inputs(rng, T, B)
        values = jnp.asarray(rng.normal(size=(T, B)), jnp.float32)
        boot = jnp.asarray(rng.normal(size=(B,)), jnp.float32)
        cfg = ImpalaLossConfig()
        pa_cfg = PopArtConfig(num_values=1, step_size=0.0)

        plain = impala_loss(
            values=values, bootstrap_value=boot, config=cfg, **inputs
        )
        pop, new_state = popart.popart_impala_loss(
            norm_values=values,
            norm_bootstrap=boot,
            tasks=jnp.zeros((B,), jnp.int32),
            state=popart.init(1),
            popart_config=pa_cfg,
            config=cfg,
            **inputs,
        )
        np.testing.assert_allclose(
            float(plain.total), float(pop.total), rtol=1e-5
        )
        np.testing.assert_allclose(np.asarray(new_state.mu), [0.0])
        np.testing.assert_allclose(np.asarray(new_state.nu), [1.0])

    def test_pg_gradient_scale_invariant_under_reward_scale(self):
        # Scaling all rewards by C should leave the policy gradient nearly
        # unchanged once stats have adapted — the multi-task point of PopArt.
        rng = np.random.default_rng(5)
        T, B = 10, 4
        inputs = _rand_inputs(rng, T, B)
        values = jnp.asarray(rng.normal(size=(T, B)), jnp.float32)
        boot = jnp.asarray(rng.normal(size=(B,)), jnp.float32)
        tasks = jnp.zeros((B,), jnp.int32)
        cfg = ImpalaLossConfig()

        def pg_grad(reward_scale, state):
            def f(logits):
                out, _ = popart.popart_impala_loss(
                    target_logits=logits,
                    behaviour_logits=inputs["behaviour_logits"],
                    norm_values=values,
                    norm_bootstrap=boot,
                    actions=inputs["actions"],
                    rewards=inputs["rewards"] * reward_scale,
                    discounts=inputs["discounts"],
                    tasks=tasks,
                    state=state,
                    popart_config=PopArtConfig(num_values=1, step_size=0.0),
                    config=cfg,
                )
                return out.logs["pg_loss"]

            return jax.grad(f)(inputs["target_logits"])

        # Adapted stats for each scale: sigma proportional to the scale.
        g1 = pg_grad(1.0, PopArtState(jnp.zeros(1), jnp.asarray([25.0])))
        g100 = pg_grad(
            100.0, PopArtState(jnp.zeros(1), jnp.asarray([250000.0]))
        )
        # Values are normalized so unnormalized V scales with sigma too;
        # advantages then scale linearly and the sigma division cancels it.
        np.testing.assert_allclose(
            np.asarray(g1), np.asarray(g100), rtol=1e-3, atol=1e-5
        )


class TestLearnerIntegration:
    def test_multitask_learner_step_updates_stats(self):
        from torched_impala_tpu.envs.fake import FakeDiscreteEnv
        from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
        from torched_impala_tpu.runtime import (
            Actor,
            Learner,
            LearnerConfig,
        )

        num_tasks = 3
        agent = Agent(
            ImpalaNet(
                num_actions=4,
                torso=MLPTorso(hidden_sizes=(32,)),
                num_values=num_tasks,
            )
        )
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-2),
            config=LearnerConfig(
                batch_size=num_tasks,
                unroll_length=5,
                popart=PopArtConfig(num_values=num_tasks, step_size=0.1),
            ),
            example_obs=np.zeros((8,), np.float32),
            rng=jax.random.key(0),
        )
        for i in range(num_tasks):
            actor = Actor(
                actor_id=i,
                env=FakeDiscreteEnv(
                    obs_shape=(8,), num_actions=4, episode_len=7,
                    reward_scale=10.0 ** i, seed=i,
                ),
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=5,
                seed=i,
                task=i,
            )
            actor.unroll_and_push()
        learner.start()
        try:
            before_mu = np.asarray(learner.popart_state.mu).copy()
            logs = learner.step_once(timeout=300)
            after = learner.popart_state
        finally:
            learner.stop()
        assert np.isfinite(float(logs["total_loss"]))
        assert not np.allclose(np.asarray(after.mu), before_mu)
        # The state survives a checkpoint round-trip.
        snap = learner.get_state()
        learner.set_state(snap)
        np.testing.assert_allclose(
            np.asarray(learner.popart_state.mu), np.asarray(after.mu)
        )


def test_popart_fused_dispatch_matches_sequential():
    """PopArt state threads through the fused lax.scan: one K=2 dispatch
    equals two sequential steps (params, mu/nu, and rescaled value head)."""
    import optax

    from torched_impala_tpu.envs.fake import FakeDiscreteEnv
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime import Actor, Learner, LearnerConfig

    num_tasks, T, K = 2, 4, 2
    results = {}
    for k in (1, K):
        agent = Agent(
            ImpalaNet(
                num_actions=3,
                torso=MLPTorso(hidden_sizes=(16,)),
                num_values=num_tasks,
            )
        )
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-2),
            config=LearnerConfig(
                batch_size=num_tasks,
                unroll_length=T,
                steps_per_dispatch=k,
                queue_capacity=K * num_tasks,
                popart=PopArtConfig(num_values=num_tasks, step_size=0.1),
            ),
            example_obs=np.zeros((8,), np.float32),
            rng=jax.random.key(0),
        )
        actors = [
            Actor(
                actor_id=i,
                env=FakeDiscreteEnv(
                    obs_shape=(8,), num_actions=3, episode_len=7,
                    reward_scale=5.0 ** i, seed=i,
                ),
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=T,
                seed=i,
                task=i,
            )
            for i in range(num_tasks)
        ]
        for _ in range(K):
            for a in actors:
                a.unroll_and_push()
        learner.start()
        try:
            for _ in range(K // k):
                learner.step_once(timeout=300)
        finally:
            learner.stop()
        results[k] = (
            jax.tree.map(np.asarray, learner.params),
            np.asarray(learner.popart_state.mu),
            np.asarray(learner.popart_state.nu),
        )

    p1, mu1, nu1 = results[1]
    pk, muk, nuk = results[K]
    np.testing.assert_allclose(mu1, muk, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(nu1, nuk, rtol=1e-5, atol=1e-7)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        p1,
        pk,
    )



class TestGradAccumPopArt:
    """grad_accum composes with PopArt via the batch-end statistics update
    (VERDICT r3 item 4): params AND (mu, nu) after one accumulated step
    must equal the unaccumulated full-batch step, for feedforward, LSTM,
    and the DP mesh — unblocking the DMLab-30 preset's HBM lever."""

    B, T, NUM_TASKS = 8, 4, 2

    def _collect(self, use_lstm):
        from torched_impala_tpu.envs.fake import FakeDiscreteEnv
        from torched_impala_tpu.runtime import Actor, ParamStore

        agent = self._agent(use_lstm)
        params = agent.init_params(jax.random.key(0), jnp.zeros((8,)))
        store = ParamStore()
        store.publish(0, params)
        trajs = []
        for i in range(self.B):
            actor = Actor(
                actor_id=i,
                env=FakeDiscreteEnv(
                    obs_shape=(8,), num_actions=3, episode_len=7,
                    reward_scale=5.0 ** (i % self.NUM_TASKS), seed=i,
                ),
                agent=agent,
                param_store=store,
                enqueue=lambda t: None,
                unroll_length=self.T,
                seed=i,
                task=i % self.NUM_TASKS,
            )
            trajs.append(actor.unroll(params))
        return trajs

    def _agent(self, use_lstm):
        from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso

        return Agent(
            ImpalaNet(
                num_actions=3,
                torso=MLPTorso(hidden_sizes=(16,)),
                num_values=self.NUM_TASKS,
                use_lstm=use_lstm,
                lstm_size=8,
            )
        )

    def _step(self, trajs, G, use_lstm=False, mesh=None):
        from torched_impala_tpu.runtime import Learner, LearnerConfig

        learner = Learner(
            agent=self._agent(use_lstm),
            optimizer=optax.sgd(1e-2),
            config=LearnerConfig(
                batch_size=self.B,
                unroll_length=self.T,
                grad_accum=G,
                popart=PopArtConfig(
                    num_values=self.NUM_TASKS, step_size=0.1
                ),
            ),
            example_obs=np.zeros((8,), np.float32),
            rng=jax.random.key(0),
            mesh=mesh,
        )
        for t in trajs:
            learner.enqueue(t)
        learner.start()
        try:
            learner.step_once(timeout=300)
        finally:
            learner.stop()
        return learner

    @pytest.mark.parametrize("use_lstm", [False, True])
    def test_matches_full_batch(self, use_lstm):
        trajs = self._collect(use_lstm)
        full = self._step(list(trajs), 1, use_lstm)
        acc = self._step(list(trajs), 4, use_lstm)
        np.testing.assert_allclose(
            np.asarray(full.popart_state.mu),
            np.asarray(acc.popart_state.mu),
            rtol=1e-6, atol=1e-8,
        )
        np.testing.assert_allclose(
            np.asarray(full.popart_state.nu),
            np.asarray(acc.popart_state.nu),
            rtol=1e-6, atol=1e-8,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            full.params,
            acc.params,
        )

    def test_matches_full_batch_on_dp_mesh(self):
        from torched_impala_tpu.parallel import make_mesh

        trajs = self._collect(False)
        full = self._step(list(trajs), 1)
        acc = self._step(
            list(trajs), 2, mesh=make_mesh(num_data=4)
        )
        np.testing.assert_allclose(
            np.asarray(full.popart_state.mu),
            np.asarray(acc.popart_state.mu),
            rtol=1e-5, atol=1e-7,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            full.params,
            acc.params,
        )


def test_multitask_popart_learns_both_scales_end_to_end():
    """DMLab-30-preset-shaped claim (VERDICT r2 item 6): two tasks whose
    reward scales differ 100x, DIFFERENT per-task action mappings, trained
    through the real Learner with PopArt — both tasks must learn (the
    small-reward task's gradient would otherwise be swamped 100x), and the
    per-task sigma must separate by roughly the scale ratio.

    Discriminative (measured ablation, same seed/budget, num_values=1, no
    PopArt): the big-reward task collapses BELOW its random baseline
    (eval 320 vs random 400 — unnormalized 100x-scale returns destabilize
    the shared net) and the small task lands at its bar (8.3 vs 8), so a
    broken PopArt path fails this test."""
    from torched_impala_tpu.envs.fake import TaskSignalEnv
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime import Learner, LearnerConfig
    from torched_impala_tpu.runtime.evaluator import run_episodes
    from torched_impala_tpu.runtime.loop import train

    SCALES = {0: 1.0, 1: 100.0}

    def factory(seed, env_index=None):
        task = (env_index or 0) % 2
        return TaskSignalEnv(
            task_id=task, reward_scale=SCALES[task], seed=seed
        )

    agent = Agent(
        ImpalaNet(
            num_actions=4, torso=MLPTorso(hidden_sizes=(32, 32)),
            num_values=2,
        )
    )
    pa_cfg = PopArtConfig(num_values=2, step_size=1e-2)
    result = train(
        agent=agent,
        env_factory=factory,
        example_obs=np.zeros((6,), np.float32),
        num_actors=2,
        envs_per_actor=2,
        learner_config=LearnerConfig(
            batch_size=8, unroll_length=12, popart=pa_cfg
        ),
        optimizer=optax.rmsprop(2e-3, decay=0.99, eps=1e-7),
        total_steps=300,
        actor_device=None,
        seed=0,
    )
    learner = result.learner

    # Per-task sigma separated by ~ the reward-scale ratio (100x).
    sig = np.asarray(popart.sigma(learner.popart_state, pa_cfg))
    ratio = sig[1] / sig[0]
    assert 20.0 < ratio < 500.0, f"sigma={sig} ratio={ratio:.1f}"

    # BOTH tasks beat a random policy by >=2x under greedy eval — in
    # particular task 0, whose unnormalized gradients are 100x smaller.
    # Random policy: episode_len * scale / num_actions.
    for task, scale in SCALES.items():
        ev = run_episodes(
            agent=agent,
            params=learner.params,
            env=TaskSignalEnv(
                task_id=task, reward_scale=scale, seed=123 + task
            ),
            num_episodes=10,
            greedy=True,
            seed=task,
        )
        random_baseline = 16 * scale / 4
        assert ev.mean_return > 2 * random_baseline, (
            f"task {task} failed to learn: {ev.mean_return:.1f} vs "
            f"random {random_baseline:.1f} (sigma={sig})"
        )


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
