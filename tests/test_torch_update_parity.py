"""Full-update torch parity: the learner's entire SGD step reproduced in torch.

The return-parity protocol (docs/RETURN_PARITY.md) rests on the claim that
every piece of the update matches the reference semantics. The V-trace
recursion already has a torch parity test (tests/test_vtrace.py); this file
extends the cross-framework check to the WHOLE training step the product
actually runs — forward (MLP policy), V-trace, loss composition
(pg + 0.5·baseline(0.5·Σerr²) + 0.01·entropy), autodiff, global-norm-40
gradient clipping, and RMSProp (optax semantics: eps inside the sqrt) —
by stepping the real jitted `Learner` and an independently written torch
implementation on identical batches and asserting the parameter
trajectories coincide for several steps.

This is the strongest parity statement runnable on a host without ALE:
if every update matches bit-for-tolerance, return curves can only diverge
through env/preprocessing differences, which the env-layer tests pin
separately.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
from torched_impala_tpu.ops import ImpalaLossConfig
from torched_impala_tpu.runtime import (
    Learner,
    LearnerConfig,
    Trajectory,
    stack_trajectories,
)

torch = pytest.importorskip("torch")

T, B, A, OBS = 6, 3, 3, 4
LR, DECAY, EPS = 1e-3, 0.99, 1e-7
MAX_GRAD_NORM = 40.0
GAMMA = 0.99
STEPS = 3


def _make_trajs(round_idx: int) -> list:
    trajs = []
    for b in range(B):
        rng = np.random.default_rng(100 * round_idx + b)
        trajs.append(
            Trajectory(
                obs=rng.normal(size=(T + 1, OBS)).astype(np.float32),
                first=np.zeros((T + 1,), np.bool_),
                actions=rng.integers(0, A, size=(T,)).astype(np.int32),
                behaviour_logits=rng.normal(size=(T, A)).astype(np.float32),
                rewards=rng.normal(size=(T,)).astype(np.float32),
                cont=(rng.uniform(size=(T,)) > 0.1).astype(np.float32),
                agent_state=(),
                actor_id=b,
                param_version=0,
                task=0,
            )
        )
    return trajs


class _TorchNet(torch.nn.Module):
    """Mirror of ImpalaNet(MLPTorso((16, 16))): 2 relu Dense + two heads."""

    def __init__(self):
        super().__init__()
        self.fc0 = torch.nn.Linear(OBS, 16)
        self.fc1 = torch.nn.Linear(16, 16)
        self.policy_head = torch.nn.Linear(16, A)
        self.value_head = torch.nn.Linear(16, 1)

    def load_flax(self, params) -> None:
        p = params["params"]

        def put(lin, leaf):
            # flax Dense kernel is [in, out]; torch Linear weight is [out, in].
            lin.weight.data = torch.from_numpy(
                np.asarray(leaf["kernel"]).T.copy()
            )
            lin.bias.data = torch.from_numpy(np.asarray(leaf["bias"]).copy())

        put(self.fc0, p["torso"]["Dense_0"])
        put(self.fc1, p["torso"]["Dense_1"])
        put(self.policy_head, p["policy_head"])
        put(self.value_head, p["value_head"])

    def forward(self, obs):
        h = torch.relu(self.fc0(obs))
        h = torch.relu(self.fc1(h))
        return self.policy_head(h), self.value_head(h)[..., 0]


def _torch_vtrace(log_rhos, discounts, rewards, values, bootstrap):
    """The scan recursion, detached (targets are constants)."""
    with torch.no_grad():
        rhos = log_rhos.exp()
        clipped_rhos = torch.clamp(rhos, max=1.0)
        cs = torch.clamp(rhos, max=1.0)
        v_tp1 = torch.cat([values[1:], bootstrap.unsqueeze(0)], dim=0)
        deltas = clipped_rhos * (rewards + discounts * v_tp1 - values)
        acc = torch.zeros(B)
        errs = torch.zeros(T, B)
        for t in reversed(range(T)):
            acc = deltas[t] + discounts[t] * cs[t] * acc
            errs[t] = acc
        vs = values + errs
        vs_tp1 = torch.cat([vs[1:], bootstrap.unsqueeze(0)], dim=0)
        pg_adv = clipped_rhos * (rewards + discounts * vs_tp1 - values)
    return vs, pg_adv


def _torch_update(net, nu, batch) -> dict:
    """One full IMPALA update in torch: loss -> grads -> clip -> RMSProp.

    `nu` is the RMSProp second-moment state (dict param-name -> tensor);
    optax semantics: p -= lr * g / sqrt(nu + eps), eps INSIDE the sqrt.
    Returns the loss logs.
    """
    obs = torch.from_numpy(batch.obs)  # [T+1, B, OBS]
    actions = torch.from_numpy(batch.actions.astype(np.int64))  # [T, B]
    behaviour_logits = torch.from_numpy(batch.behaviour_logits)
    rewards = torch.from_numpy(batch.rewards)
    discounts = GAMMA * torch.from_numpy(batch.cont)

    logits_full, values_full = net(obs)  # [T+1, B, A], [T+1, B]
    logits, values = logits_full[:-1], values_full[:-1]
    bootstrap = values_full[-1]

    log_pi = torch.log_softmax(logits, dim=-1)
    log_mu = torch.log_softmax(behaviour_logits, dim=-1)
    taken = actions.unsqueeze(-1)
    log_p_taken = log_pi.gather(-1, taken)[..., 0]
    log_mu_taken = log_mu.gather(-1, taken)[..., 0]
    log_rhos = (log_p_taken - log_mu_taken).detach()

    vs, pg_adv = _torch_vtrace(
        log_rhos, discounts, rewards, values.detach(), bootstrap.detach()
    )

    pg = -(pg_adv * log_p_taken).sum()
    bl = 0.5 * ((vs - values) ** 2).sum()
    ent = (torch.exp(log_pi) * log_pi).sum()  # negative entropy, summed
    total = pg + 0.5 * bl + 0.01 * ent

    net.zero_grad()
    total.backward()

    gnorm = torch.sqrt(
        sum((p.grad**2).sum() for p in net.parameters())
    )
    scale = torch.clamp(MAX_GRAD_NORM / (gnorm + 1e-8), max=1.0)
    with torch.no_grad():
        for name, p in net.named_parameters():
            g = p.grad * scale
            nu[name] = DECAY * nu[name] + (1.0 - DECAY) * g**2
            p -= LR * g / torch.sqrt(nu[name] + EPS)
    return {
        "total_loss": float(total.detach()),
        "pg_loss": float(pg.detach()),
        "baseline_loss": float(bl.detach()),
        "entropy_loss": float(ent.detach()),
    }


def test_full_update_torch_parity():
    """STEPS updates through the real jitted Learner == the independent
    torch implementation, parameter-for-parameter."""
    agent = Agent(
        ImpalaNet(num_actions=A, torso=MLPTorso(hidden_sizes=(16, 16)))
    )
    learner = Learner(
        agent=agent,
        optimizer=optax.rmsprop(LR, decay=DECAY, eps=EPS),
        config=LearnerConfig(
            batch_size=B,
            unroll_length=T,
            loss=ImpalaLossConfig(
                discount=GAMMA,
                reduction="sum",
                vtrace_implementation="scan",
            ),
            max_grad_norm=MAX_GRAD_NORM,
            queue_capacity=STEPS * B,
        ),
        example_obs=np.zeros((OBS,), np.float32),
        rng=jax.random.key(0),
    )
    net = _TorchNet()
    net.load_flax(jax.tree.map(np.asarray, learner.params))
    nu = {
        name: torch.zeros_like(p) for name, p in net.named_parameters()
    }

    rounds = [_make_trajs(i) for i in range(STEPS)]
    for trajs in rounds:
        for t in trajs:
            learner.enqueue(t)
    learner.start()
    try:
        for step, trajs in enumerate(rounds):
            jlogs = learner.step_once(timeout=120)
            tlogs = _torch_update(net, nu, stack_trajectories(trajs))
            for key in (
                "total_loss",
                "pg_loss",
                "baseline_loss",
                "entropy_loss",
            ):
                np.testing.assert_allclose(
                    float(jlogs[key]),
                    tlogs[key],
                    rtol=2e-4,
                    err_msg=f"step {step} log {key}",
                )
    finally:
        learner.stop()

    jp = jax.tree.map(np.asarray, learner.params)["params"]
    pairs = [
        (jp["torso"]["Dense_0"], net.fc0),
        (jp["torso"]["Dense_1"], net.fc1),
        (jp["policy_head"], net.policy_head),
        (jp["value_head"], net.value_head),
    ]
    for leaf, lin in pairs:
        np.testing.assert_allclose(
            leaf["kernel"],
            lin.weight.detach().numpy().T,
            rtol=2e-4,
            atol=1e-6,
        )
        np.testing.assert_allclose(
            leaf["bias"], lin.bias.detach().numpy(), rtol=2e-4, atol=1e-6
        )


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
