"""Control plane tests (ISSUE 12): knob bounds/quantization, the
recompile gate, signal adapters, the three policy shapes (hill climb
with guardrail reverts, target map, SLO bang-bang), the ControlLoop's
decision accounting + flight-recorder audit trail, the standard
train/serving knob sets, and the --control CLI roundtrip.

Everything here drives ``ControlLoop.tick(now=...)`` with a synthetic
clock — no threads, no sleeps — matching the doctor self-check's
deterministic style.
"""

import pytest

from torched_impala_tpu.control import (
    ControlLoop,
    DECISION_EVENT,
    CheckpointOverheadSignal,
    EwmaSignal,
    FnSignal,
    GaugeSignal,
    HillClimbPolicy,
    Knob,
    KnobSet,
    KnobSpec,
    Proposal,
    RateSignal,
    RecompileGate,
    SloHeadroomSignal,
    SloPolicy,
    TargetMapPolicy,
    build_serving_control,
    build_train_control,
)
from torched_impala_tpu.telemetry import FlightRecorder, Registry


def _spec(name="k", lo=0.0, hi=8.0, **kw):
    return KnobSpec(name, lo=lo, hi=hi, **kw)


def _knob(reg=None, **kw):
    kw.setdefault("initial", 4.0)
    spec_kw = {
        k: kw.pop(k)
        for k in ("name", "lo", "hi", "step", "settle_s", "kind",
                  "recompile", "apply", "read")
        if k in kw
    }
    return Knob(
        _spec(**spec_kw),
        telemetry=reg if reg is not None else Registry(),
        **kw,
    )


def _decisions(rec):
    """Oldest-first (kind, lineage) of control/decision instants."""
    return [
        (r[5].get("kind"), r[5])
        for r in rec.tail()
        if r[3] == DECISION_EVENT
    ]


# ---- KnobSpec ---------------------------------------------------------


class TestKnobSpec:
    def test_name_grammar_enforced(self):
        for bad in ("Bad", "9lead", "has-dash", "has/slash", ""):
            with pytest.raises(ValueError):
                _spec(name=bad)
        _spec(name="ok_name_2")  # and the happy path parses

    def test_bounds_step_kind_validation(self):
        with pytest.raises(ValueError):
            _spec(lo=4.0, hi=4.0)
        with pytest.raises(ValueError):
            _spec(lo=5.0, hi=1.0)
        with pytest.raises(ValueError):
            _spec(step=-1.0)
        with pytest.raises(ValueError):
            _spec(kind="bool")

    def test_clamp_quantizes_to_grid_and_bounds(self):
        s = _spec(lo=1.0, hi=9.0, step=2.0)
        assert s.clamp(4.2) == 5.0  # nearest grid point 1+2k
        assert s.clamp(3.9) == 3.0
        assert s.clamp(100.0) == 9.0
        assert s.clamp(-100.0) == 1.0
        si = _spec(lo=0, hi=10, kind="int")
        assert si.clamp(3.6) == 4.0
        assert isinstance(si.clamp(3.6), float)

    def test_default_step(self):
        assert _spec(step=2.0).default_step() == 2.0
        assert _spec(lo=0.0, hi=8.0).default_step() == 1.0  # range/8
        # int knobs always move by at least 1
        assert _spec(lo=0, hi=4, kind="int").default_step() == 1.0


# ---- RecompileGate ----------------------------------------------------


class TestRecompileGate:
    def test_default_deny(self):
        ok, reason = RecompileGate().check(now=0.0)
        assert not ok and "disabled" in reason

    def test_min_interval_amortization(self):
        g = RecompileGate(allow=True, min_interval_s=300.0)
        ok, _ = g.check(now=0.0)
        assert ok
        g.record(now=0.0)
        ok, reason = g.check(now=100.0)
        assert not ok and "min interval" in reason
        ok, _ = g.check(now=301.0)
        assert ok


# ---- Knob -------------------------------------------------------------


class TestKnob:
    def test_needs_initial_or_read(self):
        with pytest.raises(ValueError):
            Knob(_spec(), telemetry=Registry())

    def test_propose_applies_then_noops(self):
        reg = Registry()
        applied = []
        k = _knob(reg, apply=applied.append)
        status, detail = k.propose(6.0, now=1.0)
        assert status == "applied" and applied == [6.0]
        assert k.value == 6.0
        assert reg.snapshot()["telemetry/control/knob_k"] == 6.0
        status, _ = k.propose(6.0, now=2.0)
        assert status == "noop" and applied == [6.0]

    def test_int_apply_receives_int(self):
        applied = []
        k = _knob(kind="int", apply=applied.append)
        k.propose(6.4, now=0.0)
        assert applied == [6] and isinstance(applied[0], int)

    def test_revert_is_one_level(self):
        k = _knob()
        k.propose(6.0, now=0.0)
        assert k.revert(now=1.0) == 4.0
        assert k.value == 4.0
        assert k.revert(now=2.0) is None  # nothing left to undo

    def test_recompile_knob_refused_by_default(self):
        k = _knob(recompile=True)
        status, reason = k.propose(8.0, now=0.0)
        assert status == "refused" and "recompile-gated" in reason
        assert k.value == 4.0

    def test_recompile_knob_applies_when_allowed(self):
        k = Knob(
            _spec(recompile=True),
            gate=RecompileGate(allow=True),
            initial=4.0,
            telemetry=Registry(),
        )
        assert k.propose(8.0, now=0.0)[0] == "applied"
        # gate recorded the re-jit: immediate second move refused
        assert k.propose(2.0, now=1.0)[0] == "refused"

    def test_value_rereads_live_object(self):
        box = {"v": 4.0}
        k = _knob(read=lambda: box["v"], initial=None)
        box["v"] = 7.0  # some other actor moved the live value
        assert k.value == 7.0


class TestKnobSet:
    def test_registry_semantics(self):
        ks = KnobSet()
        a = ks.register(_knob(name="a"))
        ks.register(_knob(name="b", initial=1.0))
        assert ks["a"] is a and "a" in ks and len(ks) == 2
        assert ks.names() == ["a", "b"]
        assert ks.snapshot() == {"a": 4.0, "b": 1.0}
        with pytest.raises(ValueError):
            ks.register(_knob(name="a"))


# ---- Signals ----------------------------------------------------------


class TestSignals:
    def test_gauge_signal_reads_snapshot_key(self):
        s = GaugeSignal("perf/mfu", scale=100.0)
        assert s.read({"telemetry/perf/mfu": 0.42}, 0.0) == 42.0
        assert s.read({}, 0.0) is None
        assert s.read({"telemetry/perf/mfu": float("nan")}, 0.0) is None

    def test_fn_signal(self):
        assert FnSignal(lambda: 3.0).read({}, 0.0) == 3.0
        assert FnSignal(lambda: None).read({}, 0.0) is None
        assert FnSignal(lambda: float("nan")).read({}, 0.0) is None

    def test_ewma_signal_smooths_and_holds(self):
        s = EwmaSignal(GaugeSignal("perf/mfu"), alpha=0.5)
        assert s.read({"telemetry/perf/mfu": 1.0}, 0.0) == 1.0
        assert s.read({"telemetry/perf/mfu": 3.0}, 1.0) == 2.0
        # missing sample: hold the smoothed value instead of None
        assert s.read({}, 2.0) == 2.0

    def test_rate_signal_primes_then_rates(self):
        s = RateSignal("learner/steps")
        assert s.read({"telemetry/learner/steps": 10.0}, 0.0) is None
        assert s.read({"telemetry/learner/steps": 30.0}, 2.0) == 10.0

    def test_slo_headroom_sign_and_validation(self):
        s = SloHeadroomSignal("serving/request_wait_ms_p99", 20.0)
        assert s.read(
            {"telemetry/serving/request_wait_ms_p99": 10.0}, 0.0
        ) == pytest.approx(0.5)
        assert s.read(
            {"telemetry/serving/request_wait_ms_p99": 30.0}, 0.0
        ) == pytest.approx(-0.5)
        with pytest.raises(ValueError):
            SloHeadroomSignal("x/y", 0.0)

    def test_checkpoint_overhead_fraction(self):
        s = CheckpointOverheadSignal()
        snap1 = {
            "telemetry/resilience/checkpoint_save_ms_ms": 100.0,
            "telemetry/resilience/checkpoint_saves": 1.0,
        }
        assert s.read(snap1, 0.0) is None  # rate still priming
        snap2 = dict(snap1, **{
            "telemetry/resilience/checkpoint_saves": 3.0,
        })
        # 2 saves over 10 s at 100 ms each = 2% of wall-clock
        assert s.read(snap2, 10.0) == pytest.approx(0.02)


# ---- Policies ---------------------------------------------------------


def _hill(signal_box, **kw):
    kw.setdefault("tolerance", 0.05)
    kw.setdefault("hysteresis", 0.01)
    kw.setdefault("cooldown_s", 10.0)
    return HillClimbPolicy(FnSignal(lambda: signal_box["obj"]), **kw)


class TestHillClimbPolicy:
    def test_climbs_then_waits_out_settle(self):
        box = {"obj": 1.0}
        pol = _hill(box)
        knob = _knob(step=1.0, settle_s=5.0)
        p = pol.tick({}, 0.0, knob)
        assert p is not None and p.kind == "set" and p.target == 5.0
        knob.propose(p.target, now=0.0)
        pol.observe_result("applied", 0.0)
        assert pol.tick({}, 2.0, knob) is None  # inside settle window
        # judging tick: obj unchanged -> commit, flip direction
        assert pol.tick({}, 6.0, knob) is None
        p2 = pol.tick({}, 7.0, knob)
        assert p2 is not None and p2.target == 4.0  # now climbing down

    def test_guardrail_reverts_regression_and_cools_down(self):
        box = {"obj": 1.0}
        pol = _hill(box)
        knob = _knob(step=1.0, settle_s=2.0)
        p = pol.tick({}, 0.0, knob)
        knob.propose(p.target, now=0.0)
        pol.observe_result("applied", 0.0)
        box["obj"] = 0.5  # >5% regression within the settle window
        p = pol.tick({}, 3.0, knob)
        assert p is not None and p.kind == "revert"
        assert pol.last_objective_delta == pytest.approx(-0.5)
        knob.revert(3.0)
        pol.observe_result("reverted", 3.0)
        assert pol.tick({}, 4.0, knob) is None  # cooling down
        assert pol.tick({}, 14.0, knob) is not None  # cooldown over

    def test_hysteresis_band_flips_direction(self):
        box = {"obj": 1.0}
        pol = _hill(box)
        knob = _knob(step=1.0, settle_s=1.0)
        p = pol.tick({}, 0.0, knob)
        assert p.target == 5.0  # first move is upward
        knob.propose(p.target, now=0.0)
        pol.observe_result("applied", 0.0)
        box["obj"] = 1.001  # inside the 1% hysteresis band: didn't pay
        assert pol.tick({}, 2.0, knob) is None  # commit (no revert)
        p = pol.tick({}, 3.0, knob)
        assert p.kind == "set" and p.target == 4.0  # flipped downward

    def test_turns_around_at_bounds(self):
        box = {"obj": 1.0}
        pol = _hill(box)
        knob = _knob(lo=0.0, hi=4.0, step=1.0, initial=4.0)
        p = pol.tick({}, 0.0, knob)
        assert p is not None and p.target == 3.0  # +1 clamps: went -1

    def test_holds_without_signal(self):
        pol = HillClimbPolicy(FnSignal(lambda: None))
        assert pol.tick({}, 0.0, _knob()) is None

    def test_parameter_validation(self):
        with pytest.raises(ValueError):
            HillClimbPolicy(FnSignal(lambda: 0.0), tolerance=0.0)
        with pytest.raises(ValueError):
            HillClimbPolicy(FnSignal(lambda: 0.0), hysteresis=-0.1)


class TestTargetMapPolicy:
    def test_maps_signal_through_line(self):
        pol = TargetMapPolicy(
            FnSignal(lambda: 0.04), slope=7.5, base=1.0
        )
        knob = _knob(lo=0.25, hi=1.0, initial=1.0)
        p = pol.tick({}, 0.0, knob)
        assert p is not None and p.target == pytest.approx(0.7)
        knob.propose(p.target)
        # same signal again: clamped target == current -> hold
        assert pol.tick({}, 1.0, knob) is None

    def test_clamps_into_knob_bounds(self):
        pol = TargetMapPolicy(
            FnSignal(lambda: 1.0), slope=7.5, base=1.0
        )
        knob = _knob(lo=0.25, hi=1.0, initial=1.0)
        p = pol.tick({}, 0.0, knob)
        knob.propose(p.target)
        assert knob.value == 0.25  # floor, not -6.5


class TestSloPolicy:
    def _h(self, value):
        return FnSignal(lambda: value)

    def test_bang_bang_with_hold_band(self):
        knob = _knob(lo=0.0, hi=8.0, step=2.0)
        shrink = SloPolicy(self._h(-0.2)).tick({}, 0.0, knob)
        assert shrink.target == 2.0  # violating: one step down
        relax = SloPolicy(self._h(0.9)).tick({}, 0.0, knob)
        assert relax.target == 6.0  # ample headroom: one step up
        assert SloPolicy(self._h(0.3)).tick({}, 0.0, knob) is None

    def test_grow_on_violation_inverts(self):
        knob = _knob(lo=0.0, hi=8.0, step=2.0)
        grow = SloPolicy(self._h(-0.2), grow_on_violation=True)
        assert grow.tick({}, 0.0, knob).target == 6.0
        back = SloPolicy(self._h(0.9), grow_on_violation=True)
        assert back.tick({}, 0.0, knob).target == 2.0

    def test_cooldown_after_apply(self):
        pol = SloPolicy(self._h(-0.2), cooldown_s=5.0)
        knob = _knob(step=2.0)
        assert pol.tick({}, 0.0, knob) is not None
        pol.observe_result("applied", 0.0)
        assert pol.tick({}, 2.0, knob) is None
        assert pol.tick({}, 6.0, knob) is not None

    def test_holds_at_bound(self):
        pol = SloPolicy(self._h(-0.5))
        knob = _knob(lo=0.0, hi=8.0, step=2.0, initial=0.0)
        assert pol.tick({}, 0.0, knob) is None  # already at the floor

    def test_relax_headroom_validation(self):
        with pytest.raises(ValueError):
            SloPolicy(self._h(0.0), relax_headroom=1.5)


# ---- ControlLoop ------------------------------------------------------


class TestControlLoop:
    def _loop(self, interval_s=1.0):
        reg = Registry()
        rec = FlightRecorder(capacity=256)
        return ControlLoop(
            interval_s=interval_s, telemetry=reg, tracer=rec
        ), reg, rec

    def test_interval_validation(self):
        with pytest.raises(ValueError):
            ControlLoop(interval_s=0.0, telemetry=Registry(),
                        tracer=FlightRecorder(capacity=64))

    def test_applied_decision_audited(self):
        loop, reg, rec = self._loop()
        box = {"obj": 1.0}
        loop.bind(
            _knob(reg, step=1.0, settle_s=2.0),
            _hill(box),
        )
        assert loop.tick(now=0.0) == 1
        snap = reg.snapshot()
        assert snap["telemetry/control/decision_total"] == 1
        assert snap["telemetry/control/decision_ticks"] == 1
        assert snap["telemetry/control/knob_k"] == 5.0
        (kind, args), = _decisions(rec)
        assert kind == "set"
        assert (args["knob"], args["from"], args["to"]) == ("k", 4.0, 5.0)
        assert "hill-climb" in args["reason"]

    def test_guardrail_revert_full_cycle(self):
        """Seeded regression: apply at t=0, objective tanks, the judging
        tick reverts, and every leg lands in counters + the recorder."""
        loop, reg, rec = self._loop()
        box = {"obj": 1.0}
        loop.bind(_knob(reg, step=1.0, settle_s=2.0), _hill(box))
        loop.tick(now=0.0)  # 4 -> 5
        box["obj"] = 0.5
        assert loop.tick(now=3.0) == 1  # judged: revert 5 -> 4
        snap = reg.snapshot()
        assert snap["telemetry/control/decision_total"] == 1
        assert snap["telemetry/control/revert_total"] == 1
        assert snap["telemetry/control/knob_k"] == 4.0
        assert snap["telemetry/control/objective_delta"] == pytest.approx(
            -0.5
        )
        kinds = [k for k, _ in _decisions(rec)]
        assert kinds == ["set", "revert"]
        assert _decisions(rec)[-1][1]["to"] == 4.0

    def test_refused_recompile_audited(self):
        loop, reg, rec = self._loop()
        gated = Knob(
            _spec(name="batch", lo=1, hi=64, step=1, kind="int",
                  recompile=True),
            gate=RecompileGate(allow=False),
            initial=8,
            telemetry=reg,
        )
        loop.bind(gated, SloPolicy(FnSignal(lambda: -1.0),
                                   grow_on_violation=True))
        assert loop.tick(now=0.0) == 0  # refused counts as not-acted
        snap = reg.snapshot()
        assert snap["telemetry/control/decision_refused"] == 1
        assert snap["telemetry/control/decision_total"] == 0
        assert snap["telemetry/control/knob_batch"] == 8.0
        (kind, args), = _decisions(rec)
        assert kind == "refused" and args["from"] == args["to"] == 8.0
        assert "recompile-gated" in args["reason"]

    def test_broken_policy_does_not_take_down_siblings(self):
        loop, reg, _ = self._loop()

        class Exploding(SloPolicy):
            def tick(self, snap, now, knob):
                raise RuntimeError("boom")

        loop.bind(_knob(reg, name="bad"),
                  Exploding(FnSignal(lambda: -1.0)))
        loop.bind(_knob(reg, name="good", step=2.0),
                  SloPolicy(FnSignal(lambda: -1.0)))
        assert loop.tick(now=0.0) == 1  # sibling still acted
        assert reg.snapshot()["telemetry/control/knob_good"] == 2.0

    def test_add_knob_is_audit_only_surface(self):
        loop, reg, _ = self._loop()
        loop.add_knob(_knob(reg, name="surface"))
        assert "surface" in loop.knobs
        assert loop.tick(now=0.0) == 0  # no binding: nothing moves
        assert reg.snapshot()["telemetry/control/knob_surface"] == 4.0

    def test_thread_start_stop_idempotent(self):
        loop, _, _ = self._loop(interval_s=0.01)
        loop.start()
        loop.start()  # second start is a no-op
        loop.stop()
        assert loop._thread is None
        loop.stop()  # stop after stop is safe


# ---- standard knob sets ----------------------------------------------


class _FakeRing:
    max_reuse = 4
    replay_mix = 0.25


class _FakeCkpt:
    _interval_steps = 50


class _FakeLearner:
    _fused_fallback_k = 0


class TestBuildTrainControl:
    def test_full_composition(self):
        loop = build_train_control(
            learner=_FakeLearner(),
            traj_ring=_FakeRing(),
            checkpointer=_FakeCkpt(),
            batch_size=32,
            steps_per_dispatch=4,
            telemetry=Registry(),
            tracer=FlightRecorder(capacity=64),
        )
        assert loop.knobs.names() == [
            "batch_size",
            "checkpoint_interval_steps",
            "learner_fused_chunk",
            "replay_max_reuse",
            "replay_mix",
            "steps_per_dispatch",
        ]

    def test_fused_chunk_absent_for_k1_learner(self):
        # A K=1 learner has no [K, ...] superbatch axis to chunk —
        # binding the knob there once sliced the time axis mid-run
        # (caught live: --control auto + --traj-ring crashed the
        # learner with a broadcast shape mismatch).
        loop = build_train_control(
            learner=_FakeLearner(),
            steps_per_dispatch=1,
            telemetry=Registry(),
            tracer=FlightRecorder(capacity=64),
        )
        assert "learner_fused_chunk" not in loop.knobs.names()
        assert "steps_per_dispatch" in loop.knobs.names()

    def test_fused_chunk_bounded_by_k(self):
        loop = build_train_control(
            learner=_FakeLearner(),
            steps_per_dispatch=4,
            telemetry=Registry(),
            tracer=FlightRecorder(capacity=64),
        )
        spec = loop.knobs["learner_fused_chunk"].spec
        assert (spec.lo, spec.hi, spec.step) == (0, 4, 2)
        assert spec.clamp(8) == 4.0

    def test_steps_per_dispatch_ceiling_tracks_superbatch_max(self):
        # ISSUE 13: the superbatch ring delivers up to SUPERBATCH_MAX_K
        # per dispatch, so the gated K knob's ceiling derives from it —
        # not from a multiple of the configured K (which pinned the old
        # fused ceiling at 4*K=8 for the default K=2).
        from torched_impala_tpu.control.loop import SUPERBATCH_MAX_K

        reg = Registry()
        loop = build_train_control(
            steps_per_dispatch=2,
            allow_recompile=True,
            cooldown_s=0.0,
            telemetry=reg,
            tracer=FlightRecorder(capacity=256),
        )
        knob = loop.knobs["steps_per_dispatch"]
        assert knob.spec.hi == float(SUPERBATCH_MAX_K) > 8.0

        # With recompiles allowed, a hill climb on a monotone objective
        # must actually reach past the old K=8 ceiling.
        box = {"obj": 1.0}
        loop.bind(
            knob,
            HillClimbPolicy(
                FnSignal(lambda: box["obj"]),
                tolerance=0.05,
                hysteresis=0.01,
                cooldown_s=0.0,
            ),
        )
        now, peak = 0.0, 0.0
        for _ in range(60):
            loop.tick(now=now)
            # Outwait the recompile gate's 300s amortization window and
            # keep the objective visibly improving after every apply.
            now += 301.0
            box["obj"] *= 1.5
            peak = max(peak, knob.value)
        # The climb tops out at the new ceiling (then probes back down —
        # a monotone objective judges every move a win).
        assert peak == float(SUPERBATCH_MAX_K) > 8.0

    def test_fused_chunk_hill_climbs_past_old_k8_ceiling(self):
        # A SUPERBATCH_MAX_K learner's chunk knob spans (0, 16, 8): the
        # built-in MFU hill climb reaches full-K chunking (> 8) when the
        # signal rewards it.
        from torched_impala_tpu.control.loop import SUPERBATCH_MAX_K

        lr = _FakeLearner()
        reg = Registry()
        mfu = reg.gauge("perf/mfu")
        loop = build_train_control(
            learner=lr,
            steps_per_dispatch=SUPERBATCH_MAX_K,
            cooldown_s=0.0,
            telemetry=reg,
            tracer=FlightRecorder(capacity=256),
        )
        spec = loop.knobs["learner_fused_chunk"].spec
        assert (spec.lo, spec.hi, spec.step) == (0, 16, 8)
        now, obj, peak = 0.0, 0.1, 0
        for _ in range(50):
            mfu.set(obj)
            loop.tick(now=now)
            now += 60.0
            obj *= 1.5  # every probe judged a clear win
            peak = max(peak, lr._fused_fallback_k)
        assert peak == SUPERBATCH_MAX_K > 8

    def test_collaborators_optional(self):
        loop = build_train_control(
            telemetry=Registry(), tracer=FlightRecorder(capacity=64)
        )
        assert len(loop.knobs) == 0

    def test_shape_knobs_default_deny(self):
        loop = build_train_control(
            batch_size=32,
            telemetry=Registry(),
            tracer=FlightRecorder(capacity=64),
        )
        status, reason = loop.knobs["batch_size"].propose(64, now=0.0)
        assert status == "refused" and "recompile-gated" in reason

    def test_reuse_knob_applies_to_ring(self):
        ring = _FakeRing()
        loop = build_train_control(
            traj_ring=ring,
            telemetry=Registry(),
            tracer=FlightRecorder(capacity=64),
        )
        loop.knobs["replay_max_reuse"].propose(2, now=0.0)
        assert ring.max_reuse == 2
        loop.knobs["replay_mix"].propose(0.5, now=0.0)
        assert ring.replay_mix == 0.5


class TestBuildServingControl:
    def _server(self):
        jax = pytest.importorskip("jax")
        jax.config.update("jax_platforms", "cpu")
        import numpy as np

        from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
        from torched_impala_tpu.runtime.param_store import ParamStore
        from torched_impala_tpu.serving import (
            PolicyServer,
            VersionRegistry,
        )

        agent = Agent(
            ImpalaNet(num_actions=3, torso=MLPTorso(hidden_sizes=(8,)))
        )
        params = agent.init_params(
            jax.random.key(0), np.zeros((4,), np.float32)
        )
        store = ParamStore()
        store.publish(0, params)
        registry = VersionRegistry.serving_latest(
            store, telemetry=Registry()
        )
        return PolicyServer(
            agent=agent,
            registry=registry,
            example_obs=np.zeros((4,), np.float32),
            max_clients=8,
            max_batch=4,
            max_wait_s=0.004,
            telemetry=Registry(),
        )

    def test_serving_knobs_over_real_server(self):
        server = self._server()
        reg = Registry()
        loop = build_serving_control(
            server=server,
            slo_ms=25.0,
            telemetry=reg,
            tracer=FlightRecorder(capacity=64),
        )
        assert loop.knobs.names() == [
            "serving_max_batch",
            "serving_max_wait_ms",
        ]
        # wait knob round-trips through the server in ms
        loop.knobs["serving_max_wait_ms"].propose(2.0, now=0.0)
        assert server.max_wait_s == pytest.approx(2e-3)
        # batch knob moves the wave cap but NEVER the jit pad width
        pad0 = server.pad_batch
        loop.knobs["serving_max_batch"].propose(1, now=0.0)
        assert server.max_batch == 1 and server.pad_batch == pad0

    def test_set_max_batch_clamps_to_pad(self):
        server = self._server()
        server.set_max_batch(999)
        assert server.max_batch == server.pad_batch
        server.set_max_batch(0)
        assert server.max_batch == 1

    def test_slo_violation_shrinks_wait_window(self):
        server = self._server()
        reg = Registry()
        wait_p99 = reg.gauge("serving/request_wait_ms_p99")
        wait_p99.set(40.0)  # violating the 25 ms SLO
        loop = build_serving_control(
            server=server,
            slo_ms=25.0,
            telemetry=reg,
            tracer=FlightRecorder(capacity=64),
        )
        wait0 = server.max_wait_s
        assert loop.tick(now=0.0) >= 1
        assert server.max_wait_s < wait0


# ---- CLI / config roundtrip ------------------------------------------


class TestControlConfig:
    def test_cli_roundtrip(self):
        from torched_impala_tpu.run import build_config, parse_args

        args = parse_args(
            [
                "--config", "cartpole",
                "--control", "auto",
                "--control-interval", "2.5",
                "--fake-envs",
            ]
        )
        cfg = build_config(args)
        assert cfg.control.mode == "auto"
        assert cfg.control.interval_s == 2.5

    def test_preset_default_is_off(self):
        from torched_impala_tpu.run import build_config, parse_args

        cfg = build_config(
            parse_args(["--config", "cartpole", "--fake-envs"])
        )
        assert cfg.control.mode == "off"

    def test_validate_rejects_bad_values(self):
        import dataclasses

        from torched_impala_tpu.configs import ControlConfig

        with pytest.raises(ValueError):
            dataclasses.replace(
                ControlConfig(), mode="sometimes"
            ).validate()
        with pytest.raises(ValueError):
            dataclasses.replace(
                ControlConfig(), interval_s=0.0
            ).validate()
        ControlConfig().validate()
