"""Mixed-precision policy tests (ISSUE 16): the declarative policy
table in ops/precision.py, the full-bf16 train step's f32 accumulator
contract through a REAL learner step, bf16/f32 loss-grad tolerance
parity, the greedy-action parity gate, and the half-accumulator refusal
path at the checkpoint-restore boundary."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torched_impala_tpu import configs
from torched_impala_tpu.envs import ScriptedEnv
from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
from torched_impala_tpu.ops import ImpalaLossConfig, precision
from torched_impala_tpu.runtime import Actor, Learner, LearnerConfig


def _agent(num_actions=2):
    return Agent(
        ImpalaNet(num_actions=num_actions, torso=MLPTorso(hidden_sizes=(16,)))
    )


def _learner(train_dtype, T=5, B=3):
    return Learner(
        agent=_agent(),
        optimizer=optax.rmsprop(1e-3, decay=0.99, eps=1e-7),
        config=LearnerConfig(
            batch_size=B,
            unroll_length=T,
            loss=ImpalaLossConfig(),
            train_dtype=train_dtype,
        ),
        example_obs=np.zeros((4,), np.float32),
        rng=jax.random.key(0),
    )


def _synthetic_batch(T=5, B=3, num_actions=2, seed=0):
    rng = np.random.default_rng(seed)
    return dict(
        obs=jnp.asarray(rng.normal(size=(T + 1, B, 4)), jnp.float32),
        first=jnp.asarray(rng.uniform(size=(T + 1, B)) < 0.1),
        actions=jnp.asarray(
            rng.integers(0, num_actions, size=(T, B)), jnp.int32
        ),
        behaviour_logits=jnp.asarray(
            rng.normal(size=(T, B, num_actions)), jnp.float32
        ),
        rewards=jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
        cont=jnp.asarray((rng.uniform(size=(T, B)) > 0.05), jnp.float32),
        tasks=jnp.zeros((B,), jnp.int32),
        agent_state=(),
    )


class TestPolicyTable:
    def test_accumulator_roles_all_f32(self):
        roles = precision.accumulator_roles()
        assert "optimizer_state" in roles
        assert "popart_stats" in roles
        assert "vtrace_recursion" in roles
        for role in roles:
            assert (
                precision.MIXED_PRECISION_POLICY["accumulators"][role]
                == "float32"
            )

    def test_compute_roles_and_validation(self):
        assert "bfloat16" in precision.compute_dtypes("train_step")
        precision.validate_compute_dtype("train_step", "bfloat16")
        with pytest.raises(ValueError, match="train_step"):
            precision.validate_compute_dtype("train_step", "float16")
        with pytest.raises(ValueError, match="unknown"):
            precision.validate_compute_dtype("nonexistent_role", "float32")

    def test_cast_to_compute_floating_only(self):
        tree = {
            "w": jnp.ones((2, 2), jnp.float32),
            "step": jnp.zeros((), jnp.int32),
        }
        out = precision.cast_to_compute(tree, "bfloat16")
        assert out["w"].dtype == jnp.bfloat16
        assert out["step"].dtype == jnp.int32

    def test_half_leaves_reports_paths(self):
        tree = {"a": jnp.ones((2,), jnp.bfloat16), "b": jnp.ones((2,))}
        found = precision.half_leaves(tree)
        assert len(found) == 1
        (path, name), = found.items()
        assert "a" in path and name == "bfloat16"

    def test_assert_f32_accumulators_raises_with_role_and_path(self):
        good = {"optimizer_state": {"mu": jnp.zeros((3,), jnp.float32)}}
        precision.assert_f32_accumulators(good, context="test")
        bad = {"popart_stats": {"mu": jnp.zeros((3,), jnp.bfloat16)}}
        with pytest.raises(ValueError) as e:
            precision.assert_f32_accumulators(bad, context="test")
        assert "popart_stats" in str(e.value)
        assert "bfloat16" in str(e.value)


class TestFullBf16Step:
    def test_grad_parity_bf16_vs_f32(self):
        """The bf16 loss-grad agrees with f32 within bf16 rounding: same
        params, same batch, gradients returned in f32 either way (the
        convert_element_type transpose), close in direction and scale."""
        lr_f32 = _learner("float32")
        lr_bf16 = _learner("bfloat16")
        batch = _synthetic_batch()
        g32, logs32, _ = lr_f32._compute_grads(
            lr_f32._params, (), **batch
        )
        g16, logs16, _ = lr_bf16._compute_grads(
            lr_bf16._params, (), **batch
        )
        # Grads come back f32 regardless of the compute dtype.
        for leaf in jax.tree.leaves(g16):
            assert leaf.dtype == jnp.float32
        # Tolerance parity: bf16 has ~8 mantissa bits, so per-leaf
        # agreement is coarse but the gradient as a whole must point
        # the same way at the same magnitude.
        v32 = jnp.concatenate(
            [leaf.ravel() for leaf in jax.tree.leaves(g32)]
        )
        v16 = jnp.concatenate(
            [leaf.ravel() for leaf in jax.tree.leaves(g16)]
        )
        cos = float(
            jnp.vdot(v32, v16)
            / (jnp.linalg.norm(v32) * jnp.linalg.norm(v16))
        )
        assert cos > 0.98, cos
        norm_ratio = float(jnp.linalg.norm(v16) / jnp.linalg.norm(v32))
        assert 0.9 < norm_ratio < 1.1, norm_ratio
        loss_rel = abs(
            float(logs16["total_loss"]) - float(logs32["total_loss"])
        ) / max(1e-6, abs(float(logs32["total_loss"])))
        assert loss_rel < 0.05, loss_rel

    def test_accumulators_stay_f32_through_full_step(self):
        """One real actor-fed SGD step under train_dtype=bfloat16: the
        published params, every optimizer-state leaf, and the loss are
        exactly float32 / finite afterwards — the bf16 cast lives only
        inside the differentiated closure."""
        T, B = 5, 2
        learner = _learner("bfloat16", T=T, B=B)
        actor = Actor(
            actor_id=0,
            env=ScriptedEnv(episode_len=4),
            agent=learner._agent,
            param_store=learner.param_store,
            enqueue=learner.enqueue,
            unroll_length=T,
            seed=0,
        )
        for _ in range(B):
            actor.unroll_and_push()
        learner.start()
        logs = learner.step_once(timeout=60)
        learner.stop()
        assert np.isfinite(float(logs["total_loss"]))
        for leaf in jax.tree.leaves(learner._params):
            assert leaf.dtype == jnp.float32, leaf.dtype
        for leaf in jax.tree.leaves(learner._opt_state):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                assert leaf.dtype == jnp.float32, leaf.dtype

    def test_learner_rejects_unknown_train_dtype(self):
        with pytest.raises(ValueError):
            _learner("float16")

    def test_set_state_refuses_bf16_optimizer_moments(self):
        """Restore-boundary refusal: a checkpoint whose optimizer
        moments were saved in bf16 must be rejected before it replaces
        the live f32 state (silent-corruption guard; the doctor
        'mixed precision' row probes the PopArt flavor)."""
        learner = _learner("float32")
        state = learner.get_state()
        state["opt_state"] = jax.tree.map(
            lambda a: (
                a.astype(np.float32).astype(jnp.bfloat16)
                if np.issubdtype(np.asarray(a).dtype, np.floating)
                else a
            ),
            state["opt_state"],
        )
        with pytest.raises(ValueError, match="optimizer_state"):
            learner.set_state(state)


class TestParityGate:
    def test_cartpole_bf16_passes(self):
        cfg = dataclasses.replace(
            configs.REGISTRY["cartpole"], train_dtype="bfloat16"
        )
        ok, mismatches = configs.check_train_dtype_parity(
            cfg, seed=0, batch=8, unroll=4
        )
        assert ok and mismatches == 0

    def test_float32_short_circuits(self):
        cfg = configs.REGISTRY["cartpole"]
        assert configs.check_train_dtype_parity(cfg) == (True, 0)

    def test_make_agent_validates_train_dtype(self):
        cfg = dataclasses.replace(
            configs.REGISTRY["cartpole"], train_dtype="float16"
        )
        with pytest.raises(ValueError):
            configs.make_agent(cfg)
