"""Test harness: force CPU with 8 virtual devices BEFORE jax backends init.

Sharded/pjit code paths are exercised deterministically on an 8-device CPU
mesh (SURVEY.md §5 item 5) — no pod required. Bench runs (bench.py) use the
real TPU chip instead.

Note: this environment preloads jax at interpreter startup (sitecustomize)
with JAX_PLATFORMS=axon, so setting the env var here is too late for jax's
config — we must update `jax.config` directly. XLA_FLAGS is still read from
the environment at (lazy) backend-init time, so setting it here works.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

assert jax.default_backend() == "cpu", (
    "tests require the CPU backend; jax backends were initialized before "
    "conftest could override the platform"
)
