"""Test harness: force CPU with 8 virtual devices BEFORE jax backends init.

Sharded/pjit code paths are exercised deterministically on an 8-device CPU
mesh (SURVEY.md §5 item 5) — no pod required. Bench runs (bench.py) use the
real TPU chip instead.

Note: this environment preloads jax at interpreter startup (sitecustomize)
with JAX_PLATFORMS=axon, so setting the env var here is too late for jax's
config — we must update `jax.config` directly. XLA_FLAGS is still read from
the environment at (lazy) backend-init time, so setting it here works.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_max_isa" not in _flags:
    # Pin the CPU codegen ISA: XLA's per-process feature detection is not
    # stable on this box (AMX flags appear in some processes only), and
    # the persistent compile cache would otherwise load AOT executables
    # whose compile-time features the loading process doesn't report —
    # the loader warns about possible SIGILL. A fixed baseline makes
    # cache entries portable across processes.
    _flags = (_flags + " --xla_cpu_max_isa=AVX512").strip()
os.environ["XLA_FLAGS"] = _flags

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache across test PROCESSES: the suite's wall
# time is compile-dominated, and reruns recompile identical programs.
# Measured on this box (r5): a second full quick gate drops from ~17-21
# min to the execution floor; a single heavy compile replays in ~0.2 s
# vs 2.3 s. Keyed by jax/XLA version internally, so upgrades invalidate
# cleanly; delete the dir to force cold compiles.
import getpass

jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "JAX_TEST_CACHE_DIR",
        # Per-user path: a world-shared /tmp dir would collide across
        # users on a shared box and load executables from a predictable
        # location anyone local could write to.
        f"/tmp/jax_test_compile_cache_{getpass.getuser()}",
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

assert jax.default_backend() == "cpu", (
    "tests require the CPU backend; jax backends were initialized before "
    "conftest could override the platform"
)
