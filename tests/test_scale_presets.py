"""Scale presets smoke-tested at their REAL actor counts (VERDICT r2
weak #5: "the big presets should be smoke-tested at their real actor
counts with fake instant envs").

Feasible on this 1-core box because of the pool's forkserver start
method: workers are ~ms copy-on-write forks, so 256-512 of them boot in
tens of seconds instead of tens of minutes (see runtime/env_pool.py).
The learner budget is tiny — the claim under test is that the REAL
worker fleet boots, steps in lockstep, feeds the learner, and shuts
down cleanly at the preset's advertised scale, not that training
converges.
"""

import dataclasses

import numpy as np
import pytest

from torched_impala_tpu import configs
from torched_impala_tpu.runtime.loop import train


@pytest.mark.slow
@pytest.mark.parametrize("preset", ["breakout", "procgen"])
def test_big_preset_boots_and_trains_at_real_actor_count(preset):
    cfg = configs.REGISTRY[preset]
    assert cfg.num_actors >= 256, "these presets advertise 256-512 actors"
    assert cfg.actor_mode == "process"
    # Real fleet size and actor mode; tiny learner budget. dp is dropped:
    # the 8-virtual-device CPU mesh is exercised by test_parallel, and
    # here it would only slow the already-heavy deep-ResNet CPU step.
    cfg = dataclasses.replace(cfg, dp_devices=0)
    steps = 2
    result = train(
        agent=configs.make_agent(cfg),
        env_factory=configs.make_env_factory(cfg, fake=True),
        example_obs=configs.example_obs(cfg),
        num_actors=cfg.num_actors,
        learner_config=configs.make_learner_config(cfg),
        optimizer=configs.make_optimizer(cfg),
        total_steps=steps,
        seed=0,
        envs_per_actor=cfg.envs_per_actor,
        actor_mode=cfg.actor_mode,
    )
    assert result.learner.num_steps == steps
    assert (
        result.num_frames
        == steps * cfg.unroll_length * cfg.batch_size
    )
    # No episode-count assert: the tiny budget spreads ~5 steps per env
    # across the huge fleet, far short of the fake's 1000-step episodes —
    # the exact num_frames above already proves every unroll came from
    # real lockstep env stepping. No worker needed a restart to get here
    # (fake envs can't crash).
    assert result.actor_restarts == 0
