"""Native batch assembler tests: exact equivalence with the numpy path.

The C++ batcher must produce bit-identical batches to `stack_trajectories`
for every dtype/layout the runtime emits — including the NON-contiguous
`buf[:, i]` views VectorActor pushes.
"""

import numpy as np
import pytest

from torched_impala_tpu.native import get_batcher_lib
from torched_impala_tpu.native.stack import fast_stack_trajectories
from torched_impala_tpu.runtime.learner import stack_trajectories
from torched_impala_tpu.runtime.types import Trajectory

pytestmark = pytest.mark.skipif(
    get_batcher_lib() is None, reason="native batcher unavailable"
)


def _traj(rng, T=5, obs_shape=(84, 84, 4), A=6, state=(), **kw):
    return Trajectory(
        obs=rng.integers(0, 256, size=(T + 1, *obs_shape)).astype(np.uint8),
        first=rng.uniform(size=(T + 1,)) < 0.2,
        actions=rng.integers(0, A, size=(T,)).astype(np.int32),
        behaviour_logits=rng.normal(size=(T, A)).astype(np.float32),
        rewards=rng.normal(size=(T,)).astype(np.float32),
        cont=(rng.uniform(size=(T,)) > 0.1).astype(np.float32),
        agent_state=state,
        **kw,
    )


def _assert_batches_equal(a: Trajectory, b: Trajectory):
    import jax

    for name in ("obs", "first", "actions", "behaviour_logits", "rewards",
                 "cont", "task"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=name,
        )
    assert a.param_version == b.param_version
    la, lb = jax.tree.leaves(a.agent_state), jax.tree.leaves(b.agent_state)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestEquivalence:
    def test_feedforward_pixel_batch(self):
        rng = np.random.default_rng(0)
        trajs = [
            _traj(rng, param_version=i * 10, task=i % 3) for i in range(4)
        ]
        _assert_batches_equal(
            fast_stack_trajectories(trajs), stack_trajectories(trajs)
        )

    def test_lstm_state_leaves(self):
        rng = np.random.default_rng(1)
        trajs = [
            _traj(
                rng,
                obs_shape=(8,),
                state=(
                    rng.normal(size=(1, 16)).astype(np.float32),
                    rng.normal(size=(1, 16)).astype(np.float32),
                ),
            )
            for _ in range(3)
        ]
        _assert_batches_equal(
            fast_stack_trajectories(trajs), stack_trajectories(trajs)
        )

    def test_noncontiguous_vector_actor_views(self):
        # Exactly what VectorActor pushes: column views of [T+1, E, ...]
        # buffers (non-contiguous over the time axis).
        rng = np.random.default_rng(2)
        T, E = 6, 5
        obs_block = rng.integers(0, 256, size=(T + 1, E, 84, 84, 4)).astype(
            np.uint8
        )
        logits_block = rng.normal(size=(T, E, 6)).astype(np.float32)
        trajs = []
        for i in range(E):
            t = _traj(rng, T=T)
            trajs.append(
                t._replace(
                    obs=obs_block[:, i], behaviour_logits=logits_block[:, i]
                )
            )
        assert not trajs[0].obs.flags["C_CONTIGUOUS"]
        _assert_batches_equal(
            fast_stack_trajectories(trajs), stack_trajectories(trajs)
        )

    def test_large_batch_multithreaded_path(self):
        # The obs leaf must exceed batcher.cpp's 16MB threading threshold so
        # the concurrent copy_slot fan-out actually runs: 32 x 21 x 84*84*4
        # = ~19MB. Results must still be exact.
        rng = np.random.default_rng(3)
        trajs = [_traj(rng, T=20) for _ in range(32)]
        _assert_batches_equal(
            fast_stack_trajectories(trajs, max_threads=4),
            stack_trajectories(trajs),
        )


class TestLearnerIntegration:
    def test_learner_uses_native_batcher_end_to_end(self):
        import jax
        import optax

        from torched_impala_tpu.envs.fake import FakeDiscreteEnv
        from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
        from torched_impala_tpu.runtime import Actor, Learner, LearnerConfig

        agent = Agent(
            ImpalaNet(num_actions=3, torso=MLPTorso(hidden_sizes=(16,)),
                      use_lstm=True, lstm_size=8)
        )
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-3),
            config=LearnerConfig(
                batch_size=2, unroll_length=4, native_batcher=True
            ),
            example_obs=np.zeros((6,), np.float32),
            rng=jax.random.key(0),
        )
        actor = Actor(
            actor_id=0,
            env=FakeDiscreteEnv(obs_shape=(6,), num_actions=3),
            agent=agent,
            param_store=learner.param_store,
            enqueue=learner.enqueue,
            unroll_length=4,
        )
        for _ in range(2):
            actor.unroll_and_push()
        learner.start()
        try:
            logs = learner.step_once(timeout=120)
        finally:
            learner.stop()
        assert np.isfinite(float(logs["total_loss"]))


def test_benchmark_report():
    # Not an assertion-bench (machines vary): prints the speedup so CI logs
    # carry the signal. Kept cheap.
    import time

    rng = np.random.default_rng(4)
    trajs = [_traj(rng, T=20) for _ in range(16)]
    fast_stack_trajectories(trajs)  # warm the .so
    t0 = time.perf_counter()
    for _ in range(5):
        fast_stack_trajectories(trajs)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(5):
        stack_trajectories(trajs)
    t_numpy = time.perf_counter() - t0
    print(f"native={t_native * 200:.1f}ms/batch numpy={t_numpy * 200:.1f}"
          f"ms/batch speedup={t_numpy / t_native:.2f}x")


if __name__ == "__main__":
    pytest.main([__file__, "-q", "-s"])
