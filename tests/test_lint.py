"""impala-lint suite tests (ISSUE 7): framework, the four checkers
against seeded fixtures, the full-tree tier-1 gate, the lock-graph
coverage acceptance, the check_metric_names shim, the thread
excepthook, and the shm cleanup-under-kill regression.

Fixture files live under tests/lint_fixtures/ — they are PARSED by the
checkers, never imported, so each can seed violations freely. Every
rule has one positive (the *_bad fixture makes it fire) and one
negative (the *_good fixture stays silent).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint import (  # noqa: E402
    apply_baseline,
    load_baseline,
    load_files,
    parse_directives,
    run_all,
)
from tools.lint.core import (  # noqa: E402
    SourceFile,
    apply_inline_allows,
    framework_findings,
)
from tools.lint import (  # noqa: E402
    donation,
    dtypes,
    ipa,
    jitb,
    metrics,
    sharding,
    shm,
    threads,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


def fixture(name: str) -> SourceFile:
    path = os.path.join(FIXTURES, name)
    with open(path, encoding="utf-8") as f:
        return SourceFile(path, name, f.read())


def rules_of(findings):
    return {f.rule for f in findings}


# ---- framework ----------------------------------------------------------


class TestFramework:
    def test_parse_directives(self):
        ds = parse_directives(
            "x = 1  # lint: allow(thread-safety), guarded-by(_lock)"
        )
        assert [(d.name, d.arg) for d in ds] == [
            ("allow", "thread-safety"),
            ("guarded-by", "_lock"),
        ]
        assert parse_directives("x = 1  # plain comment") == []

    def test_malformed_directive_is_a_finding(self):
        sf = SourceFile("<m>", "m.py", "x = 1  # lint: guard-by(_lock)\n")
        fs = framework_findings([sf])
        assert [f.rule for f in fs] == ["framework/bad-annotation"]
        assert "guard-by(_lock)" in fs[0].message

    def test_parse_error_is_a_finding(self):
        sf = SourceFile("<p>", "p.py", "def broken(:\n")
        fs = framework_findings([sf])
        assert [f.rule for f in fs] == ["framework/parse-error"]

    def test_allow_suppresses_only_matching_rule(self):
        sf = fixture("jit_bad.py")
        found = jitb.check([sf])
        assert found, "fixture must produce findings"
        # allow(all) on every line would drop them; a non-matching allow
        # must not.
        kept = apply_inline_allows([sf], found)
        assert kept == found

    def test_baseline_suppression_and_staleness(self, tmp_path):
        sf = fixture("shm_bad.py")
        found = shm.check([sf])
        assert found
        f0 = found[0]
        bl = tmp_path / "baseline.txt"
        bl.write_text(
            f"{f0.rule} {f0.baseline_key} grandfathered: fixture\n"
            "shm-lifecycle/no-close gone.py::Gone._shm stale entry\n"
        )
        entries = load_baseline(str(bl))
        result = apply_baseline(found, entries)
        assert f0 not in result.findings
        assert len(result.suppressed) >= 1
        assert [e.key for e in result.stale_baseline] == [
            "gone.py::Gone._shm"
        ]

    def test_baseline_requires_justification(self, tmp_path):
        bl = tmp_path / "baseline.txt"
        bl.write_text("shm-lifecycle/no-close some.py::C._shm\n")
        with pytest.raises(ValueError, match="justification"):
            load_baseline(str(bl))


# ---- thread-safety checker ----------------------------------------------


class TestThreadChecker:
    def test_bad_fixture_fires_every_rule(self):
        found = threads.check([fixture("threads_bad.py")])
        rules = rules_of(found)
        assert "thread-safety/unguarded-attr" in rules
        assert "thread-safety/mixed-locks" in rules
        assert "thread-safety/unknown-lock" in rules
        assert "thread-safety/lock-cycle" in rules
        keys = {f.baseline_key for f in found}
        assert "threads_bad.py::UnguardedCounter.count" in keys
        assert "threads_bad.py::MixedLocks.state" in keys

    def test_lock_cycle_direct_and_through_call(self):
        found = [
            f
            for f in threads.check([fixture("threads_bad.py")])
            if f.rule == "thread-safety/lock-cycle"
        ]
        cycles = " | ".join(f.message for f in found)
        assert "LockCycle._lock1" in cycles
        assert "IndirectCycle._lock_x" in cycles  # via helper() call

    def test_good_fixture_is_clean(self):
        sf = fixture("threads_good.py")
        assert apply_inline_allows([sf], threads.check([sf])) == []

    def test_lock_graph_covers_required_subsystems(self):
        """Acceptance: the lock-order graph must span the learner,
        serving, resilience, and traj_ring locks."""
        nodes, _edges = threads.build_lock_graph(load_files(REPO))
        required = {
            "Learner._auto_lock",  # learner
            "PolicyServer._service_lock",  # serving
            "PolicyServer._cond",
            "ShmRingPump._lock",
            "AsyncCheckpointer._pending_lock",  # resilience
            "TrajectoryRing._cond",  # traj_ring
            "ActorSupervisor._lock",
        }
        assert required <= nodes, f"missing: {required - nodes}"


# ---- jit-boundary checker -----------------------------------------------


class TestJitChecker:
    def test_bad_fixture_fires_every_rule(self):
        found = jitb.check([fixture("jit_bad.py")])
        rules = rules_of(found)
        assert "jit-boundary/host-sync-in-jit" in rules
        assert "jit-boundary/host-sync-in-hot-loop" in rules
        assert "jit-boundary/donated-arg-alive" in rules
        msgs = " | ".join(f.message for f in found)
        assert ".item()" in msgs
        assert "print" in msgs
        assert "asarray" in msgs
        assert "float()" in msgs
        assert "device_get" in msgs  # traced through the self-call chain

    def test_donated_arg_site_names_symbol(self):
        found = [
            f
            for f in jitb.check([fixture("jit_bad.py")])
            if f.rule == "jit-boundary/donated-arg-alive"
        ]
        assert len(found) == 1
        assert "params" in found[0].message

    def test_good_fixture_is_clean(self):
        sf = fixture("jit_good.py")
        assert apply_inline_allows([sf], jitb.check([sf])) == []


# ---- shm-lifecycle checker ----------------------------------------------


class TestShmChecker:
    def test_bad_fixture_fires_every_rule(self):
        found = shm.check([fixture("shm_bad.py")])
        by_rule = {}
        for f in found:
            by_rule.setdefault(f.rule, []).append(f.baseline_key)
        assert "shm_bad.py::LeakyOwner._shm" in by_rule[
            "shm-lifecycle/no-close"
        ]
        assert set(by_rule["shm-lifecycle/no-unlink"]) == {
            "shm_bad.py::LeakyOwner._shm",
            "shm_bad.py::CloseButNoUnlink._shm",
        }
        assert by_rule["shm-lifecycle/local-no-finally"] == [
            "shm_bad.py::attach_and_maybe_leak.shm"
        ]

    def test_good_fixture_is_clean(self):
        sf = fixture("shm_good.py")
        assert apply_inline_allows([sf], shm.check([sf])) == []


# ---- telemetry checker + shim -------------------------------------------


class TestMetricsChecker:
    def test_bad_fixture_fires_every_rule(self):
        found = metrics.check([fixture("metrics_bad.py")])
        rules = rules_of(found)
        assert rules == {
            "telemetry/name-grammar",
            "telemetry/type-fork",
            "telemetry/literal-key",
            "telemetry/subfamily-prefix",
            "telemetry/agg-prefix",
            "telemetry/trace-grammar",
            "telemetry/trace-closed-set",
        }
        msgs = " | ".join(f.message for f in found)
        assert "NoSlash" in msgs
        assert "registered it as gauge" in msgs
        assert "Bad.Trace" in msgs
        # rules 3b/3c/3d/3e/3f each fire on their own family
        assert "resilience metric" in msgs
        assert "serving metric" in msgs
        assert "replay metric" in msgs
        assert "perf metric" in msgs
        assert "control metric" in msgs
        # 3e is a PREFIX match: perf/mfuzzy fires even though it
        # contains "mfu"
        assert "perf/mfuzzy" in msgs
        # 3f likewise: control/decisions_made fires even though it
        # contains "decision"
        assert "control/decisions_made" in msgs
        # 3g: the fleet_/route_ serving sub-families are prefix
        # matches too — fleetsize/routesplit fire despite containing
        # "fleet"/"route"
        assert "serving/fleetsize" in msgs
        assert "serving/routesplit" in msgs
        # 3h: alerts/* is a prefix match — alerts/burning fires even
        # though it contains "burn"
        assert "alerts metric" in msgs
        assert "alerts/burning" in msgs
        # 3j: health/* (training-health plane) is a prefix match too —
        # health/clipping fires even though it contains "clip"
        assert "health metric" in msgs
        assert "health/orphan_series" in msgs
        assert "health/clipping" in msgs
        # 3i: aggregated proc<h>w<w>/ keys — malformed label and
        # malformed remainder both fire
        assert "proc0wx/pool/step_ms" in msgs
        assert "proc0w1/0bad/step" in msgs
        # 3i multi-host grammar (ISSUE 18): h is a REAL process index,
        # so multi-digit hosts are legal (proc12w3 lives in the good
        # fixture) but junk inside the label still fires
        assert "proc1x2w0/pool/step_ms" in msgs
        # 4b closed set: serving/rollout is pinned, serving/rollback
        # is not
        assert "serving/rollback" in msgs
        # prose string and malformed-charset literal must NOT flag
        assert "bad key here" not in msgs and "bad/Key" not in msgs

    def test_good_fixture_is_clean(self):
        sf = fixture("metrics_good.py")
        assert metrics.check([sf]) == []

    def test_shim_check_matches_framework(self, tmp_path):
        """tools/check_metric_names.py stays a faithful shim: same
        findings, legacy string format."""
        import importlib.util

        pkg = tmp_path / "torched_impala_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text(
            'reg.counter("NoSlash")\n'
            'reg.gauge("pool/depth")\n'
            'reg.timer("pool/depth")\n'
        )
        spec = importlib.util.spec_from_file_location(
            "check_metric_names_shim",
            os.path.join(REPO, "tools", "check_metric_names.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        errors = mod.check(str(tmp_path))
        assert len(errors) == 2
        assert errors[0].startswith("torched_impala_tpu/bad.py:1: ")
        assert "NoSlash" in errors[0]
        assert "registered it as gauge" in errors[1]


# ---- interprocedural engine (ISSUE 11) ----------------------------------


def _graph(*files):
    """Build a CallGraph from (rel, text) pairs."""
    return ipa.build(
        [SourceFile(f"<{rel}>", rel, text) for rel, text in files]
    )


class TestCallGraph:
    def test_cycle_terminates_and_visits_each_once(self):
        g = _graph(
            (
                "cyc.py",
                "def a():\n    b()\n"
                "def b():\n    c()\n"
                "def c():\n    a()\n",
            )
        )
        seen = [(fi.qualname, hop) for fi, hop in g.callees("cyc:a", 10)]
        # cycle-safe: terminates, each function once at its minimum
        # distance, the root itself never re-yielded
        assert dict(seen) == {"b": 1, "c": 2}

    def test_import_alias_resolution(self):
        g = _graph(
            ("helpers.py", "def work():\n    pass\n"),
            (
                "caller.py",
                "import helpers as h\n"
                "from helpers import work as w\n"
                "def direct():\n    h.work()\n"
                "def renamed():\n    w()\n",
            ),
        )
        for caller in ("caller:direct", "caller:renamed"):
            edges = g.calls_out[caller]
            assert [e.callee.fid for e in edges] == ["helpers:work"]

    def test_relative_import_resolution(self):
        g = _graph(
            ("pkg/util.py", "def f():\n    pass\n"),
            (
                "pkg/mod.py",
                "from . import util\n"
                "from .util import f as g\n"
                "def a():\n    util.f()\n"
                "def b():\n    g()\n",
            ),
        )
        for caller in ("pkg.mod:a", "pkg.mod:b"):
            edges = g.calls_out[caller]
            assert [e.callee.fid for e in edges] == ["pkg.util:f"]

    def test_self_method_and_base_class_resolution(self):
        g = _graph(
            (
                "cls.py",
                "class Base:\n"
                "    def shared(self):\n        pass\n"
                "class Child(Base):\n"
                "    def own(self):\n        pass\n"
                "    def run(self):\n"
                "        self.own()\n"
                "        self.shared()\n",
            )
        )
        callees = {
            e.callee.fid for e in g.calls_out["cls:Child.run"]
        }
        assert callees == {"cls:Child.own", "cls:Base.shared"}

    def test_constructor_resolves_to_init(self):
        g = _graph(
            (
                "ctor.py",
                "class Thing:\n"
                "    def __init__(self, size):\n        pass\n"
                "def make():\n    return Thing(4)\n",
            )
        )
        edges = g.calls_out["ctor:make"]
        assert [e.callee.fid for e in edges] == ["ctor:Thing.__init__"]
        assert edges[0].is_constructor

    def test_bound_arguments_maps_positional_and_kw(self):
        g = _graph(
            (
                "args.py",
                "def callee(x, y, *, z=None):\n    pass\n"
                "def caller():\n    callee(1, y=2, z=3)\n",
            )
        )
        site = g.calls_out["args:caller"][0]
        bound = ipa.bound_arguments(site.callee, site.node)
        assert {k: type(v).__name__ for k, v in bound.items()} == {
            "x": "Constant",
            "y": "Constant",
            "z": "Constant",
        }


# ---- sharding-contract checker (ISSUE 11) --------------------------------


class TestShardingChecker:
    def test_bad_fixture_fires_every_rule(self):
        found = sharding.check([fixture("sharding_bad.py")])
        rules = rules_of(found)
        assert "sharding/undeclared-axis" in rules
        assert "sharding/ad-hoc-spec" in rules
        assert "sharding/spec-table-mismatch" in rules
        assert "sharding/spec-arity-mismatch" in rules
        msgs = " | ".join(f.message for f in found)
        # direct sites: P literal, collective axis, Mesh axis tuple
        assert "'batch'" in msgs
        assert "'sequence'" in msgs
        assert "'modle'" in msgs
        # interprocedural: string literal bound through forwards_axis
        # into takes_axis(axis_name=...) two hops from the collective
        assert "'sequenze'" in msgs
        # arity: 3-dim spec on the rank-2 jnp.zeros((4, 8))
        assert "rank 2" in msgs

    def test_good_fixture_is_clean(self):
        assert sharding.check([fixture("sharding_good.py")]) == []

    def test_tensor_table_is_self_consistent(self):
        """Every TENSOR_TABLE spec uses only MESH_AXES names — checked
        on the real repo tables (the fallback load path)."""
        mesh_axes, tensor_table, _, errs = sharding._load_tables([])
        assert errs == []
        axes = set(mesh_axes)
        for name, spec in tensor_table.items():
            for entry in spec:
                if entry is None:
                    continue
                parts = (
                    entry if isinstance(entry, tuple) else (entry,)
                )
                assert set(parts) <= axes, (name, spec)

    def test_batch_placement_table_loads_and_is_consistent(self):
        """BATCH_ROLES/BATCH_PLACEMENT parse as pure literals (the
        fallback load path) and every role's logical name resolves
        against TENSOR_TABLE in both layouts."""
        _, tensor_table, placement, errs = sharding._load_tables([])
        assert errs == []
        roles = placement["__roles__"]
        assert "obs" in roles and "agent_state" in roles
        for layout in ("plain", "superbatch"):
            entries = placement[layout]
            assert set(entries) == set(roles)
            for role, (logical, dim) in entries.items():
                assert logical in tensor_table, (layout, role)
                assert isinstance(dim, int)

    def _as_runtime(self, name):
        rel = f"torched_impala_tpu/runtime/{name}"
        path = os.path.join(FIXTURES, name)
        with open(path, encoding="utf-8") as f:
            return SourceFile(f"<{rel}>", rel, f.read())

    def test_feedpath_bad_fixture_fires(self):
        found = sharding.check([self._as_runtime("feedpath_bad.py")])
        assert "sharding/feed-path-placement" in rules_of(found)
        msgs = " | ".join(f.message for f in found)
        assert "feed_shardings" in msgs

    def test_feedpath_good_fixture_is_clean(self):
        found = sharding.check([self._as_runtime("feedpath_good.py")])
        assert found == []

    def test_feedpath_rule_scoped_to_runtime(self):
        """The same NamedSharding construction outside runtime/ does
        not trip the feed-path rule (other modules legitimately build
        shardings from the table's specs)."""
        rel = "torched_impala_tpu/parallel/other.py"
        path = os.path.join(FIXTURES, "feedpath_bad.py")
        with open(path, encoding="utf-8") as f:
            sf = SourceFile(f"<{rel}>", rel, f.read())
        found = sharding.check([sf])
        assert "sharding/feed-path-placement" not in rules_of(found)


# ---- interprocedural donation checker (ISSUE 11) -------------------------


class TestDonationChecker:
    def test_bad_fixture_flags_read_after_wrapper_donation(self):
        found = donation.check([fixture("donation_bad.py")])
        assert rules_of(found) == {"donation/donated-arg-alive"}
        assert len(found) == 1
        f = found[0]
        # the finding names the live symbol and the donating wrapper
        assert "p" in f.message and "train" in f.message
        assert f.baseline_key == "donation_bad.py::Learner.run:p"

    def test_good_fixture_is_clean(self):
        assert donation.check([fixture("donation_good.py")]) == []


# ---- dtype-policy checker (ISSUE 11) -------------------------------------


class TestDtypeChecker:
    def test_bad_fixture_fires_stats_and_cast_rules(self):
        found = dtypes.check([fixture("dtype_bad.py")])
        rules = rules_of(found)
        assert "dtype/stats-not-f32" in rules
        assert "dtype/cast-outside-jit-root" in rules
        stats = [f for f in found if f.rule == "dtype/stats-not-f32"]
        msgs = " | ".join(f.message for f in stats)
        # direct half creation (nu) AND 1-hop flow through halved() (mu)
        assert "nu" in msgs
        assert "mu" in msgs and "halved()" in msgs

    def test_accumulator_module_rule_fires_on_vtrace_named_file(self):
        found = dtypes.check([fixture("dtype_vtrace_bad.py")])
        assert "dtype/half-in-accumulator-module" in rules_of(found)

    def test_good_fixture_is_clean(self):
        """Half cast inside a jit root and f32 stats: silent."""
        assert dtypes.check([fixture("dtype_good.py")]) == []

    def test_fused_compute_dtype_allowlist_is_surgical(self):
        """The ONE sanctioned half binding in vtrace_pallas.py (the
        fused epilogue's compute-dtype allow-list, ISSUE 13) is exempt
        from the accumulator-module rule — but only that assignment;
        any other half token in the same file still fires, and the same
        binding name in a DIFFERENT vtrace module is not exempt."""
        allowed_rel = "torched_impala_tpu/ops/vtrace_pallas.py"
        body = (
            "_FUSED_COMPUTE_DTYPES = (\n"
            '    "float32",\n'
            '    "bfloat16",\n'
            ")\n"
        )
        sf = SourceFile(f"<{allowed_rel}>", allowed_rel, body)
        assert dtypes.check([sf]) == []
        # A second, unsanctioned half token in the allow-listed file.
        sf2 = SourceFile(
            f"<{allowed_rel}>",
            allowed_rel,
            body + 'rogue = "bfloat16"\n',
        )
        found = dtypes.check([sf2])
        assert rules_of(found) == {"dtype/half-in-accumulator-module"}
        assert [f.line for f in found] == [5]
        # Same binding name in another vtrace-named module: not exempt.
        other_rel = "torched_impala_tpu/ops/vtrace_other.py"
        sf3 = SourceFile(f"<{other_rel}>", other_rel, body)
        assert "dtype/half-in-accumulator-module" in rules_of(
            dtypes.check([sf3])
        )

    def test_policy_table_rogue_accumulator_fires(self):
        """A bf16 accumulator role in MIXED_PRECISION_POLICY (the
        declarative table the allow-list is derived from, ISSUE 16)
        fires dtype/policy-accumulator-not-f32 — and a rogue table
        that drops half_bindings stops exempting vtrace_pallas."""
        rel = "torched_impala_tpu/ops/precision.py"
        rogue = (
            "MIXED_PRECISION_POLICY = {\n"
            '    "accumulators": {\n'
            '        "optimizer_state": "float32",\n'
            '        "popart_stats": "bfloat16",\n'
            "    },\n"
            '    "compute": {"torso": ("float32",)},\n'
            '    "half_bindings": (),\n'
            "}\n"
        )
        sf = SourceFile(f"<{rel}>", rel, rogue)
        found = dtypes.check([sf])
        assert "dtype/policy-accumulator-not-f32" in rules_of(found)
        bad = [
            f
            for f in found
            if f.rule == "dtype/policy-accumulator-not-f32"
        ]
        assert len(bad) == 1 and "popart_stats" in bad[0].message
        assert bad[0].line == 4  # the rogue value's own line
        # With half_bindings emptied, the previously sanctioned
        # vtrace_pallas binding is no longer exempt.
        vt_rel = "torched_impala_tpu/ops/vtrace_pallas.py"
        vt = SourceFile(
            f"<{vt_rel}>",
            vt_rel,
            '_FUSED_COMPUTE_DTYPES = ("float32", "bfloat16")\n',
        )
        assert "dtype/half-in-accumulator-module" in rules_of(
            dtypes.check([sf, vt])
        )

    def test_policy_table_on_disk_is_clean_and_parseable(self):
        """The committed table literal_evals and declares every
        accumulator role float32 (the property rule 4 polices)."""
        import ast as ast_mod
        import os

        from tools.lint.core import REPO

        path = os.path.join(
            REPO, "torched_impala_tpu", "ops", "precision.py"
        )
        with open(path, encoding="utf-8") as f:
            tree = ast_mod.parse(f.read())
        assign = dtypes._policy_assign(tree)
        assert assign is not None
        table = ast_mod.literal_eval(assign.value)
        assert set(table["accumulators"].values()) == {"float32"}
        assert (
            "torched_impala_tpu/ops/vtrace_pallas.py",
            "_FUSED_COMPUTE_DTYPES",
        ) in set(map(tuple, table["half_bindings"]))


# ---- transitive hot-loop analysis (ISSUE 11 satellite) -------------------


class TestHotLoopDepth:
    def test_sync_one_call_deep_needs_depth_one(self):
        sf = fixture("hotloop_depth_bad.py")
        assert jitb.check([sf], hot_loop_depth=0) == []
        found = jitb.check([sf], hot_loop_depth=1)
        assert rules_of(found) == {
            "jit-boundary/host-sync-in-hot-loop"
        }
        msg = found[0].message
        assert "step_once" in msg and "_serve_loop" in msg
        assert "1 call(s) deep" in msg

    def test_good_fixture_is_clean_at_depth_one(self):
        sf = fixture("hotloop_depth_good.py")
        assert jitb.check([sf], hot_loop_depth=1) == []

    def test_tree_is_clean_at_depth_one(self):
        """Acceptance: the transitive audit passes on HEAD — the one
        real finding (the learner's stack-reuse capability probe) is
        triaged with an inline allow."""
        result = run_all(REPO, hot_loop_depth=1)
        assert result.findings == [], "\n".join(
            f.format() for f in result.findings
        )


# ---- full tree: the tier-1 gate -----------------------------------------


class TestFullTree:
    def test_tree_lints_clean_with_baseline(self):
        """Acceptance: `python -m tools.lint` exits 0 on the tree —
        zero non-baselined findings across all seven checkers
        (thread-safety, jit-boundary, shm-lifecycle, telemetry,
        sharding, donation, dtype)."""
        result = run_all(REPO)
        assert result.findings == [], "\n".join(
            f.format() for f in result.findings
        )
        # The baseline must carry no stale entries (a fixed finding
        # leaves a suppression behind) and real justifications.
        assert result.stale_baseline == [], [
            e.key for e in result.stale_baseline
        ]
        for _f, entry in result.suppressed:
            assert len(entry.justification) >= 10, entry

    def test_thread_safety_reports_real_finding_without_baseline(self):
        """Acceptance: the thread-safety checker surfaces >= 1 genuine
        pre-existing finding on this tree — suppressed only by the
        justified baseline (the Learner train-state trio)."""
        result = run_all(REPO, baseline_path=None)
        ts = [
            f
            for f in result.findings
            if f.rule.startswith("thread-safety/")
        ]
        assert len(ts) >= 1
        keys = {f.baseline_key for f in ts}
        assert (
            "torched_impala_tpu/runtime/learner.py::Learner._params"
            in keys
        )

    def test_cli_exit_codes(self, tmp_path):
        env = dict(os.environ, PYTHONPATH=REPO)
        clean = subprocess.run(
            [sys.executable, "-m", "tools.lint"],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
        )
        assert clean.returncode == 0, clean.stderr
        assert "impala-lint: OK" in clean.stderr
        # A seeded violation flips the exit code.
        pkg = tmp_path / "torched_impala_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text('reg.counter("NoSlash")\n')
        dirty = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.lint",
                "--root",
                str(tmp_path),
                "--baseline",
                "none",
            ],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
        )
        assert dirty.returncode == 1, dirty.stderr
        assert "NoSlash" in dirty.stderr

    def test_cli_strict_baseline(self, tmp_path):
        env = dict(os.environ, PYTHONPATH=REPO)
        # On HEAD every baseline entry is live: strict passes.
        strict = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--strict-baseline"],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
        )
        assert strict.returncode == 0, strict.stderr
        # A stale entry flips the exit code only under --strict-baseline.
        pkg = tmp_path / "torched_impala_tpu"
        pkg.mkdir()
        (pkg / "clean.py").write_text("x = 1\n")
        bl = tmp_path / "baseline.txt"
        bl.write_text(
            "telemetry/name-grammar gone.py::nowhere long-gone entry\n"
        )
        base = [
            sys.executable,
            "-m",
            "tools.lint",
            "--root",
            str(tmp_path),
            "--baseline",
            str(bl),
        ]
        lax = subprocess.run(
            base, cwd=REPO, env=env, capture_output=True, text=True
        )
        assert lax.returncode == 0, lax.stderr
        hard = subprocess.run(
            base + ["--strict-baseline"],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
        )
        assert hard.returncode == 1, hard.stderr
        assert "stale" in hard.stderr.lower()

    def test_cli_github_format(self, tmp_path):
        env = dict(os.environ, PYTHONPATH=REPO)
        pkg = tmp_path / "torched_impala_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text('reg.counter("NoSlash")\n')
        dirty = subprocess.run(
            [
                sys.executable,
                "-m",
                "tools.lint",
                "--root",
                str(tmp_path),
                "--baseline",
                "none",
                "--format",
                "github",
            ],
            cwd=REPO,
            env=env,
            capture_output=True,
            text=True,
        )
        assert dirty.returncode == 1
        line = [
            ln
            for ln in dirty.stdout.splitlines()
            if ln.startswith("::error ")
        ]
        assert line, dirty.stdout + dirty.stderr
        assert "file=torched_impala_tpu/bad.py" in line[0]
        assert "line=1" in line[0]
        assert "title=telemetry/name-grammar" in line[0]

    def test_doctor_lint_selfcheck_passes(self):
        from torched_impala_tpu.doctor import _check_lint

        status, detail = _check_lint()
        assert status == "ok", detail

    def test_doctor_sharding_selfcheck_passes(self):
        from torched_impala_tpu.doctor import _check_sharding

        status, detail = _check_sharding()
        assert status == "ok", detail


# ---- satellite: thread excepthook ---------------------------------------


class TestThreadExcepthook:
    def test_crash_reaches_telemetry_and_stderr(self, capfd):
        from torched_impala_tpu.telemetry import (
            Registry,
            install_thread_excepthook,
            uninstall_thread_excepthook,
        )
        import torched_impala_tpu.telemetry.excepthook as eh

        fresh = Registry()
        orig_get = eh.get_registry
        try:
            install_thread_excepthook()
            # Route the hook's registry lookup at a fresh registry.
            eh.get_registry = lambda: fresh
            t = threading.Thread(
                target=lambda: (_ for _ in ()).throw(
                    RuntimeError("boom-in-thread")
                ),
                name="doomed",
            )
            t.start()
            t.join(timeout=5)
        finally:
            eh.get_registry = orig_get
            uninstall_thread_excepthook()
        snap = fresh.snapshot()
        assert snap.get("telemetry/runtime/thread_crashes") == 1, snap
        err = capfd.readouterr().err
        assert "doomed" in err and "RuntimeError" in err
        assert "thread_crashes" in err

    def test_install_is_idempotent_and_uninstall_restores(self):
        from torched_impala_tpu.telemetry import (
            install_thread_excepthook,
            uninstall_thread_excepthook,
        )
        import torched_impala_tpu.telemetry.excepthook as eh

        before = threading.excepthook
        install_thread_excepthook()
        hooked = threading.excepthook
        install_thread_excepthook()  # second install: no rewrap
        assert threading.excepthook is hooked
        assert eh.installed()
        uninstall_thread_excepthook()
        assert threading.excepthook is before
        assert not eh.installed()

    def test_loop_train_installs_hook(self):
        """loop.train arms the hook (satellite wiring)."""
        import inspect

        from torched_impala_tpu.runtime import loop

        src = inspect.getsource(loop.train)
        assert "install_thread_excepthook()" in src

    def test_server_start_installs_hook(self):
        import inspect

        from torched_impala_tpu.serving.server import PolicyServer

        src = inspect.getsource(PolicyServer.start)
        assert "install_thread_excepthook()" in src


# ---- satellite: shm cleanup under kill_env_worker -----------------------


def _lint_scripted_factory(seed: int, env_index=None):
    from torched_impala_tpu.envs.fake import ScriptedEnv

    env = ScriptedEnv(episode_len=5)
    env.task_id = 0 if env_index is None else env_index
    return env


class TestShmCleanupUnderKill:
    def test_pool_segment_unlinked_after_worker_kill(self):
        """Negative regression (ISSUE 7 satellite): the lifecycle
        checker found no leak on the chaos-kill path, so prove it
        dynamically — SIGKILL a worker mid-run (the kill_env_worker
        fault's exact mechanism), let the pool repair it, close the
        pool, and assert the SharedMemory NAME is gone from the
        system (attach raises FileNotFoundError)."""
        from multiprocessing import shared_memory

        from torched_impala_tpu.runtime.env_pool import ProcessEnvPool

        pool = ProcessEnvPool(
            env_factory=_lint_scripted_factory,
            num_workers=2,
            envs_per_worker=1,
            obs_shape=(4,),
            obs_dtype=np.float32,
            base_seed=0,
            max_restarts=4,
        )
        name = pool._shm.name
        lane_name = pool._snap_lane._shm.name
        try:
            pool.reset_all()
            obs, rewards, dones, _ = pool.step_all(np.zeros(2, np.int32))
            assert obs.shape == (2, 4)
            # SIGKILL worker 0 — exactly what chaos kill_env_worker does.
            os.kill(pool._procs[0].pid, signal.SIGKILL)
            pool._procs[0].join(timeout=10)
            deadline = time.monotonic() + 30
            repaired = False
            while time.monotonic() < deadline:
                _, _, dones, _ = pool.step_all(np.zeros(2, np.int32))
                if pool.restarts >= 1:
                    repaired = True
                    break
            assert repaired, "pool never repaired the killed worker"
            # The segments are still attachable while the pool lives.
            for seg in (name, lane_name):
                probe = shared_memory.SharedMemory(name=seg)
                probe.close()
        finally:
            pool.close()
        # After close(): close + unlink ran on every exit path — both
        # names must be GONE (this is what the static no-unlink rule
        # guarantees; here we prove it held under a worker kill). The
        # ISSUE 17 snapshot lane rides the same lifecycle as the obs
        # ring: a SIGKILLed publisher must not leak the fan-in segment
        # either.
        for seg in (name, lane_name):
            with pytest.raises(FileNotFoundError):
                shared_memory.SharedMemory(name=seg)

    def test_serving_ring_owner_unlinks_after_backpressure(self):
        """Same proof for the serving shm ring's RingBackpressure path:
        a client that dies in backpressure must not leak the segment —
        the OWNING side unlinks at close regardless."""
        from multiprocessing import shared_memory

        from torched_impala_tpu.serving.shm_ring import (
            RingBackpressure,
            ShmRingClient,
            ShmServingRing,
        )

        ring = ShmServingRing(
            capacity=1, obs_shape=(4,), obs_dtype=np.float32
        )
        name = ring._shm.name
        attached = ShmServingRing.attach(ring.descriptor())
        client = ShmRingClient(attached)
        client.submit(np.zeros(4, np.float32), True)
        with pytest.raises(RingBackpressure):
            # Nobody serves: the one slot stays REQUEST, the second
            # submit hits backpressure and raises.
            client.submit(
                np.zeros(4, np.float32), True, timeout_s=0.05
            )
        attached.close()  # attach side: close only (no unlink)
        ring.close()  # owner: close + unlink
        with pytest.raises(FileNotFoundError):
            shared_memory.SharedMemory(name=name)
