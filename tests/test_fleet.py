"""Fleet serving tests (ISSUE 14): weighted least-loaded routing edge
cases (exact weighted split, death mid-request with exactly-one retry,
draining blocks vs. dead raises), rollout-during-burst per-wave version
uniformity, int8 quantization + the parity gate end to end through a
fleet, the serving chaos faults (kill_server_mid_wave failover,
corrupt_pinned_version bounded retry, wedge_shm_ring), the load
generator's arrival sampling + accounting closure, ParamStore publish
listeners, and the per-replica control-plane binding."""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")

from torched_impala_tpu.control.loop import build_serving_control  # noqa: E402
from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso  # noqa: E402
from torched_impala_tpu.resilience.chaos import (  # noqa: E402
    ChaosInjector,
    ChaosPlan,
    Fault,
)
from torched_impala_tpu.runtime.param_store import ParamStore  # noqa: E402
from torched_impala_tpu.serving import (  # noqa: E402
    FleetClient,
    PolicyServer,
    ServerClosed,
    ServingFleet,
    ShmRingClient,
    ShmRingPump,
    ShmServingRing,
    TrafficShape,
    VersionRegistry,
    corrupt_scales,
    dequantize_params,
    greedy_action_parity,
    quantize_params,
    run_load,
)
from torched_impala_tpu.serving.fleet import ACTIVE, DEAD, DRAINING  # noqa: E402
from torched_impala_tpu.serving.quant import (  # noqa: E402
    quant_axis_for,
    quantization_report,
)
from torched_impala_tpu.telemetry import Registry  # noqa: E402

OBS_DIM = 6
NUM_ACTIONS = 5


def make_agent() -> Agent:
    return Agent(
        ImpalaNet(
            num_actions=NUM_ACTIONS,
            torso=MLPTorso(hidden_sizes=(16,)),
        )
    )


@pytest.fixture(scope="module")
def agent():
    return make_agent()


@pytest.fixture(scope="module")
def params(agent):
    return agent.init_params(
        jax.random.key(0), np.zeros((OBS_DIM,), np.float32)
    )


def make_fleet(agent, params, replicas=2, versions=1, start=False, **kw):
    """Fresh (fleet, store) with v0..versions-1 published and the fleet
    label pinned to the LATEST. Servers are NOT started unless asked —
    routing tests exercise acquire/release without serve threads."""
    store = ParamStore()
    for v in range(versions):
        store.publish(v, params)
    kw.setdefault("telemetry", Registry())
    kw.setdefault("max_clients", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_s", 0.0)
    fleet = ServingFleet(
        agent=agent,
        store=store,
        example_obs=np.zeros((OBS_DIM,), np.float32),
        replicas=replicas,
        version=versions - 1,
        **kw,
    )
    if start:
        fleet.start()
    return fleet, store


def obs_batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, OBS_DIM)).astype(np.float32)


def direct_greedy(agent, params, obs):
    out = agent.step(
        params,
        jax.random.key(0),
        obs,
        np.ones((obs.shape[0],), np.bool_),
        agent.initial_state(obs.shape[0]),
    )
    return np.argmax(np.asarray(out.policy_logits), axis=-1)


# ---- router: weighted least-loaded picks -------------------------------


class TestRouter:
    def test_weighted_split_is_exact(self, agent, params):
        """40 acquires with weights (3, 1) and no releases must split
        exactly 30/10: the min-key ((inflight+1)/weight, -weight, name)
        is deterministic water-filling, not a sampling approximation."""
        fleet, _ = make_fleet(agent, params, weights=(3.0, 1.0))
        try:
            picks = [fleet.acquire().name for _ in range(40)]
            assert picks.count("r0") == 30
            assert picks.count("r1") == 10
        finally:
            fleet.close()

    def test_equal_weights_alternate_least_loaded(self, agent, params):
        fleet, _ = make_fleet(agent, params)
        try:
            # Ties (equal score, equal weight) break on name: r0 first.
            assert [fleet.acquire().name for _ in range(4)] == [
                "r0", "r1", "r0", "r1",
            ]
        finally:
            fleet.close()

    def test_release_restores_inflight_and_feeds_ewma(self, agent, params):
        fleet, _ = make_fleet(agent, params, replicas=1)
        try:
            rep = fleet.acquire()
            assert rep.inflight == 1 and rep.ewma_ms is None
            fleet.release(rep, latency_ms=10.0)
            assert rep.inflight == 0
            assert rep.ewma_ms == 10.0  # first sample seeds the EWMA
            rep = fleet.acquire()
            fleet.release(rep, latency_ms=20.0)
            # alpha=0.2 default: 0.2*20 + 0.8*10
            assert rep.ewma_ms == pytest.approx(12.0)
            # Failed releases never pollute the latency estimate.
            rep = fleet.acquire()
            fleet.release(rep, latency_ms=999.0, ok=False)
            assert rep.ewma_ms == pytest.approx(12.0)
        finally:
            fleet.close()

    def test_exclude_and_prefer(self, agent, params):
        fleet, _ = make_fleet(agent, params)
        try:
            assert fleet.acquire(exclude=("r0",)).name == "r1"
            assert fleet.acquire(prefer="r1").name == "r1"
        finally:
            fleet.close()

    def test_acquire_blocks_through_draining_then_resumes(
        self, agent, params
    ):
        """DRAINING is temporary by contract — the router parks the
        caller instead of failing over, and wakes it on return."""
        fleet, _ = make_fleet(agent, params, replicas=1)
        try:
            rep = fleet.replica("r0")
            with fleet._cond:
                rep.state = DRAINING

            def restore():
                time.sleep(0.1)
                with fleet._cond:
                    rep.state = ACTIVE
                    fleet._cond.notify_all()

            t = threading.Thread(target=restore, daemon=True)
            t.start()
            assert fleet.acquire(timeout_s=5.0).name == "r0"
            t.join()
        finally:
            fleet.close()

    def test_acquire_times_out_while_draining(self, agent, params):
        fleet, _ = make_fleet(agent, params, replicas=1)
        try:
            with fleet._cond:
                fleet.replica("r0").state = DRAINING
            with pytest.raises(TimeoutError, match="no ACTIVE replica"):
                fleet.acquire(timeout_s=0.05)
        finally:
            fleet.close()

    def test_acquire_raises_when_all_dead(self, agent, params):
        fleet, _ = make_fleet(agent, params)
        try:
            for rep in fleet.replicas():
                fleet.mark_dead(rep, reason="test")
            assert fleet.states() == {"r0": DEAD, "r1": DEAD}
            with pytest.raises(ServerClosed, match="no live replica"):
                fleet.acquire()
        finally:
            fleet.close()

    def test_replica_lookup_raises_on_unknown_name(self, agent, params):
        fleet, _ = make_fleet(agent, params)
        try:
            with pytest.raises(KeyError):
                fleet.replica("r9")
        finally:
            fleet.close()


# ---- failover: replica death mid-request -------------------------------


class TestFailover:
    def test_death_mid_request_retries_exactly_once(self, agent, params):
        """r0 dies under the router's nose: the client's first attempt
        lands on it, fails ServerClosed, marks it dead, and retries ON A
        DIFFERENT replica — exactly once, observably (FleetResult.retried
        + the retry counter)."""
        reg = Registry()
        fleet, _ = make_fleet(
            agent, params, start=True, telemetry=reg
        )
        try:
            # The fleet still believes r0 is ACTIVE; kill its server
            # out-of-band, the way a crashed process would look.
            fleet.replica("r0").server.kill(reason="test crash")
            with FleetClient(fleet) as client:
                res = client.act_full(obs_batch(1)[0], True)
                assert res.retried is True
                assert res.replica == "r1"
                assert 0 <= res.action < NUM_ACTIONS
                assert fleet.states()["r0"] == DEAD
                assert reg.counter("serving/route_retry_total").value == 1
                # The survivor serves the next request with no retry.
                res2 = client.act_full(obs_batch(1, seed=1)[0], True)
                assert res2.retried is False and res2.replica == "r1"
                assert reg.counter("serving/route_retry_total").value == 1
        finally:
            fleet.close()

    def test_second_failure_propagates(self, agent, params):
        """One retry is the whole budget: with every replica dead the
        client surfaces ServerClosed instead of spinning."""
        fleet, _ = make_fleet(agent, params, start=True)
        try:
            for rep in fleet.replicas():
                rep.server.kill(reason="test crash")
            with FleetClient(fleet) as client:
                with pytest.raises(ServerClosed):
                    client.act_full(obs_batch(1)[0], True)
                assert all(s == DEAD for s in fleet.states().values())
                # Fast-fail from then on: the router refuses up front.
                with pytest.raises(ServerClosed, match="no live replica"):
                    client.act_full(obs_batch(1)[0], True)
        finally:
            fleet.close()


# ---- draining rollouts under live load ---------------------------------


class TestRollout:
    def test_rollout_during_burst_keeps_waves_version_uniform(
        self, agent, params
    ):
        """The acceptance property at test scale: a rollout lands while
        client threads hammer the fleet; every (replica, wave) pair must
        serve exactly one version and nothing may error or drop."""
        fleet, store = make_fleet(agent, params, start=True)
        results = []
        errors = []
        lock = threading.Lock()

        def worker(seed):
            obs = obs_batch(40, seed=seed)
            try:
                with FleetClient(fleet) as client:
                    for i in range(40):
                        r = client.act_full(obs[i], True)
                        with lock:
                            results.append(r)
            except Exception as e:  # pragma: no cover - failure detail
                with lock:
                    errors.append(e)

        try:
            threads = [
                threading.Thread(target=worker, args=(s,)) for s in range(3)
            ]
            for t in threads:
                t.start()
            time.sleep(0.02)
            store.publish(1, params)
            out = fleet.rollout(1, timeout_s=30.0)
            for t in threads:
                t.join()
            assert errors == []
            assert out == {"version": 1, "replicas": ["r0", "r1"]}
            assert len(results) == 120
            by_wave = {}
            for r in results:
                by_wave.setdefault((r.replica, r.wave), set()).add(r.version)
            assert all(len(v) == 1 for v in by_wave.values())
            versions = {r.version for r in results}
            assert versions <= {0, 1}
            # Post-rollout traffic is on the new version.
            with FleetClient(fleet) as client:
                assert client.act_full(obs_batch(1)[0], True).version == 1
        finally:
            fleet.close()

    def test_rollout_unknown_version_raises_before_draining(
        self, agent, params
    ):
        fleet, _ = make_fleet(agent, params, start=True)
        try:
            with pytest.raises(KeyError):
                fleet.rollout(99)
            assert fleet.states() == {"r0": ACTIVE, "r1": ACTIVE}
        finally:
            fleet.close()

    def test_rollout_skips_dead_replica(self, agent, params):
        fleet, store = make_fleet(agent, params, start=True)
        try:
            fleet.replica("r1").server.kill(reason="test")
            fleet.mark_dead(fleet.replica("r1"), reason="test")
            store.publish(1, params)
            out = fleet.rollout(1, timeout_s=30.0)
            assert out == {"version": 1, "replicas": ["r0"]}
        finally:
            fleet.close()

    def test_warm_precaches_the_serving_dtype(self, agent, params):
        """rollout()'s WARM phase: quantization happens off-rotation, so
        the first post-pin wave reuses the cache instead of paying it."""
        fleet, _ = make_fleet(
            agent, params, replicas=1, versions=2, dtype="int8"
        )
        try:
            server = fleet.replica("r0").server
            assert 0 not in server._cast_cache
            server.warm(0)
            assert 0 in server._cast_cache
        finally:
            fleet.close()
        # float32 serving has nothing to pre-resolve: warm is a no-op.
        fleet, _ = make_fleet(agent, params, replicas=1)
        try:
            server = fleet.replica("r0").server
            server.warm(0)
            assert len(server._cast_cache) == 0
        finally:
            fleet.close()


# ---- int8 quantization + the parity gate -------------------------------


class TestQuant:
    def test_layout_globs_select_channel_axes(self):
        assert quant_axis_for("params/Dense_0/kernel") == -1
        assert quant_axis_for("params/embed/embedding") == -1
        assert quant_axis_for("params/Dense_0/bias") is None
        assert quant_axis_for("params/LayerNorm_0/scale") is None
        assert quant_axis_for("opt_state/count") is None  # no match

    def test_roundtrip_error_bounded_by_half_scale(self):
        rng = np.random.default_rng(3)
        w = rng.normal(size=(8, 4)).astype(np.float32)
        qp = quantize_params({"m": {"kernel": w}})
        q = np.asarray(qp.q["m"]["kernel"])
        scale = np.asarray(qp.scale["m"]["kernel"])
        assert q.dtype == np.int8
        assert scale.shape == (1, 4)  # per-output-channel, keepdims
        np.testing.assert_allclose(
            scale[0], np.abs(w).max(axis=0) / 127.0, rtol=1e-6
        )
        dq = np.asarray(dequantize_params(qp)["m"]["kernel"])
        # Symmetric round-to-nearest: error <= scale/2 per channel.
        assert np.all(np.abs(dq - w) <= scale / 2 + 1e-7)

    def test_pass_through_leaves_survive_untouched(self, params):
        qp = quantize_params(params)
        rpt = quantization_report(qp)
        assert rpt["quantized_leaves"] >= 1
        assert rpt["quantized_leaves"] < rpt["leaves"]  # biases pass through
        assert rpt["int8_bytes"] > 0 and rpt["scale_bytes"] > 0
        flat = jax.tree_util.tree_flatten_with_path(qp.q)[0]
        for path, leaf in flat:
            path_s = "/".join(str(getattr(p, "key", p)) for p in path)
            if path_s.endswith("bias"):
                assert leaf.dtype == np.float32

    def test_parity_gate_passes_and_seeded_corruption_fails(
        self, agent, params
    ):
        obs = obs_batch(16, seed=7)
        ok, mismatches = greedy_action_parity(
            agent, params, obs, dtype="int8"
        )
        assert ok and mismatches == 0
        bad = lambda p: dequantize_params(  # noqa: E731
            corrupt_scales(quantize_params(p))
        )
        ok, mismatches = greedy_action_parity(
            agent, params, obs, cast_fn=bad
        )
        assert not ok and mismatches > 0

    def test_int8_fleet_serves_parity_actions(self, agent, params):
        """End to end through routing: an int8 fleet's greedy actions
        equal the f32 direct actions (the gate's promise)."""
        fleet, _ = make_fleet(
            agent, params, replicas=1, dtype="int8", start=True
        )
        try:
            obs = obs_batch(5, seed=21)
            expected = direct_greedy(agent, params, obs)
            with FleetClient(fleet) as client:
                got = [client.act(obs[i], True) for i in range(5)]
            assert np.array_equal(np.asarray(got), expected)
        finally:
            fleet.close()


# ---- chaos: the serving fault kinds ------------------------------------


class TestServingChaos:
    def test_kill_server_mid_wave_fails_over(self, agent, params):
        """The harness fault, not a hand-rolled kill: the first wave's
        replica dies between dequeue and compute; the request must still
        be answered by the survivor, retried exactly once."""
        fleet, _ = make_fleet(agent, params, start=True)
        injector = ChaosInjector(
            ChaosPlan([Fault(kind="kill_server_mid_wave", at=1)]),
            telemetry=Registry(),
        )
        injector.install(fleets=[fleet])
        try:
            with FleetClient(fleet) as client:
                res = client.act_full(obs_batch(1)[0], True)
            assert res.retried is True
            assert len(injector.fired) == 1
            states = fleet.states()
            assert sorted(states.values()) == [ACTIVE, DEAD]
        finally:
            fleet.close()

    def test_corrupt_pinned_version_is_a_bounded_outage(self, agent, params):
        """Corrupting the SHARED store poisons every replica: the wave
        fails at trace time, each server kills itself rather than wedge,
        and the client surfaces ServerClosed after its single retry —
        correlated failure must cost one retry, not a retry storm."""
        fleet, _ = make_fleet(agent, params, start=True)
        injector = ChaosInjector(
            ChaosPlan([Fault(kind="corrupt_pinned_version", at=1)]),
            telemetry=Registry(),
        )
        injector.install(fleets=[fleet])
        try:
            with FleetClient(fleet) as client:
                with pytest.raises(ServerClosed):
                    client.act_full(obs_batch(1)[0], True)
            assert len(injector.fired) == 1
            assert all(s == DEAD for s in fleet.states().values())
        finally:
            fleet.close()

    def test_wedge_shm_ring_is_latency_not_errors(self, agent, params):
        """A wedged pump stalls the scan for duration_s; the client sees
        a slow answer, never a wrong or failed one."""
        store = ParamStore()
        store.publish(0, params)
        registry = VersionRegistry.serving_latest(
            store, telemetry=Registry()
        )
        server = PolicyServer(
            agent=agent,
            registry=registry,
            example_obs=np.zeros((OBS_DIM,), np.float32),
            telemetry=Registry(),
            max_clients=8,
            max_batch=4,
            max_wait_s=0.0,
        )
        server.start()
        ring = ShmServingRing(
            capacity=4, obs_shape=(OBS_DIM,), obs_dtype=np.float32
        )
        pump = ShmRingPump(server)
        injector = ChaosInjector(
            ChaosPlan(
                [Fault(kind="wedge_shm_ring", at=1, duration_s=0.3)]
            ),
            telemetry=Registry(),
        )
        injector.install(pumps=[pump])
        try:
            pump.attach(ring, greedy=True)
            obs = obs_batch(2, seed=9)
            expected = direct_greedy(agent, params, obs)
            rc = ShmRingClient(ring)
            # Submit BEFORE the pump starts: its very first scan fires
            # the wedge, so the queued request waits out the full stall.
            rc.submit(obs[0], True)
            t0 = time.monotonic()
            pump.start()
            got0 = rc.result(timeout_s=30.0)[0]
            assert time.monotonic() - t0 >= 0.25  # absorbed the stall
            got1 = rc.act(obs[1], True)  # recovered: fault is one-shot
            assert np.array_equal(
                np.asarray([got0, got1]), expected
            )
            assert len(injector.fired) == 1
            assert rc.outstanding == 0
        finally:
            pump.stop()
            server.close()
            ring.close()


# ---- load generator: arrivals + accounting -----------------------------


class TestLoadgen:
    def test_shape_validation(self):
        with pytest.raises(ValueError):
            TrafficShape(kind="sawtooth")
        with pytest.raises(ValueError):
            TrafficShape(rate_rps=0.0)
        with pytest.raises(ValueError):
            TrafficShape(kind="diurnal", amplitude=1.0)
        with pytest.raises(ValueError):
            TrafficShape(kind="bursty", burst_duty=1.0)
        with pytest.raises(ValueError):
            TrafficShape(kind="diurnal", period_s=0.0)

    def test_poisson_arrivals_match_rate(self):
        shape = TrafficShape(kind="poisson", rate_rps=500.0, duration_s=2.0)
        ts = shape.arrival_times(np.random.default_rng(0))
        # Poisson(1000): 3 sigma is ~±95 arrivals.
        assert 850 <= len(ts) <= 1150
        assert np.all(np.diff(ts) >= 0)
        assert ts[0] >= 0.0 and ts[-1] < shape.duration_s
        assert shape.peak_rate() == 500.0

    def test_bursty_and_diurnal_mean_rates(self):
        bursty = TrafficShape(
            kind="bursty", rate_rps=300.0, duration_s=4.0, period_s=1.0
        )
        assert bursty.peak_rate() == 1200.0  # default burst = 4x base
        n = len(bursty.arrival_times(np.random.default_rng(1)))
        assert abs(n - 1200) <= 300  # mean preserved across the duty cycle
        diurnal = TrafficShape(
            kind="diurnal",
            rate_rps=300.0,
            duration_s=4.0,
            period_s=2.0,
            amplitude=0.5,
        )
        assert diurnal.peak_rate() == pytest.approx(450.0)
        n = len(diurnal.arrival_times(np.random.default_rng(2)))
        assert abs(n - 1200) <= 300

    def test_run_load_accounting_closes(self, agent, params):
        """Every offered arrival lands in exactly one outcome bucket and
        the headline rates are recomputable from the buckets."""
        fleet, _ = make_fleet(agent, params, replicas=1, start=True)
        try:
            shape = TrafficShape(
                kind="poisson", rate_rps=300.0, duration_s=0.5
            )
            report = run_load(
                fleet=fleet,
                shape=shape,
                slo_ms=100.0,
                example_obs=np.zeros((OBS_DIM,), np.float32),
                clients=4,
                seed=5,
                disconnect_frac=0.25,
            )
            assert report.offered > 0
            assert report.offered == (
                report.ok
                + report.expired
                + report.disconnected
                + report.failed
            )
            assert report.failed == 0 and report.expired == 0
            assert report.disconnected > 0  # chaos clients hung up
            assert report.ok_within_slo <= report.ok
            assert len(report.latencies_ms) == report.ok
            assert report.goodput_rps == pytest.approx(
                report.ok_within_slo / shape.duration_s
            )
            summary = report.summary()
            for key in ("offered", "ok", "goodput_rps", "p99_ms"):
                assert key in summary
        finally:
            fleet.close()


# ---- ParamStore publish listeners (the rollout feed) -------------------


class TestPublishListeners:
    def test_listener_add_remove_and_error_isolation(self):
        store = ParamStore()
        seen = []
        fn = store.add_publish_listener(seen.append)

        def broken(_v):
            raise RuntimeError("observer bug")

        store.add_publish_listener(broken)
        store.publish(1, {"w": 1})  # broken listener must not stall this
        assert seen == [1]
        store.remove_publish_listener(fn)
        store.publish(2, {"w": 2})
        assert seen == [1]

    def test_fleet_tracks_latest_published(self, agent, params):
        reg = Registry()
        fleet, store = make_fleet(agent, params, telemetry=reg)
        try:
            gauge = reg.gauge("serving/fleet_latest_published")
            assert gauge.value == 0
            store.publish(7, params)
            assert gauge.value == 7
        finally:
            fleet.close()
        # close() detaches the listener: later publishes are not seen.
        store.publish(9, params)
        assert gauge.value == 7


# ---- control plane: per-replica knob binding ---------------------------


class TestFleetSloSpecs:
    def test_objective_table_shape(self, agent, params):
        """The fleet's SloSpec table (ISSUE 17) plugs into the
        burn-rate engine: a dead replica held past the alert windows
        must burn the active-floor budget, while a healthy fleet burns
        nothing."""
        from torched_impala_tpu.telemetry import AlertEngine
        from torched_impala_tpu.telemetry.tracing import FlightRecorder

        fleet, _ = make_fleet(agent, params)
        try:
            specs = fleet.slo_specs(slo_ms=40.0)
            by_name = {s.name: s for s in specs}
            assert by_name["fleet_route_p99"].key == (
                "serving/route_latency_ms_p99"
            )
            assert by_name["fleet_route_p99"].objective == 40.0
            floor = by_name["fleet_active_floor"]
            assert floor.kind == "lower"
            assert floor.is_bad(1.0)  # one of two replicas: degraded
            assert not floor.is_bad(2.0)
            reg = Registry()
            eng = AlertEngine(
                [
                    type(floor)(
                        **{
                            **floor.__dict__,
                            "fast_window_s": 0.5,
                            "slow_window_s": 1.0,
                        }
                    )
                ],
                registry=reg,
                recorder=FlightRecorder(capacity=16),
            )
            t, fired = 0.0, False
            while t <= 2.0:
                if eng.evaluate(
                    {"telemetry/serving/fleet_active": 1.0}, now=t
                ):
                    fired = True
                t += 0.1
            assert fired
        finally:
            fleet.close()


class TestFleetControl:
    def test_per_replica_knob_names(self, agent, params):
        fleet, _ = make_fleet(agent, params)
        try:
            loop = build_serving_control(fleet=fleet, telemetry=Registry())
            assert loop.knobs.names() == [
                "serving_max_batch_r0",
                "serving_max_batch_r1",
                "serving_max_wait_ms_r0",
                "serving_max_wait_ms_r1",
            ]
        finally:
            fleet.close()

    def test_exactly_one_of_server_or_fleet(self, agent, params):
        fleet, _ = make_fleet(agent, params)
        try:
            with pytest.raises(ValueError, match="exactly one"):
                build_serving_control()
            with pytest.raises(ValueError, match="exactly one"):
                build_serving_control(
                    server=fleet.replica("r0").server, fleet=fleet
                )
        finally:
            fleet.close()
