"""Model zoo tests: shapes, step/unroll parity, LSTM reset semantics."""

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torched_impala_tpu.models import (
    Agent,
    AtariDeepTorso,
    AtariShallowTorso,
    ImpalaNet,
    MLPTorso,
)


def _make_agent(use_lstm, num_actions=4, obs_shape=(8,), torso=None):
    net = ImpalaNet(
        num_actions=num_actions,
        torso=torso if torso is not None else MLPTorso(hidden_sizes=(16, 16)),
        use_lstm=use_lstm,
        lstm_size=12,
    )
    agent = Agent(net)
    params = agent.init_params(
        jax.random.key(0), jnp.zeros(obs_shape, jnp.float32)
    )
    return agent, params


@pytest.mark.parametrize(
    "torso,obs_shape,feat",
    [
        (MLPTorso(hidden_sizes=(32, 16)), (8,), 16),
        (AtariShallowTorso(), (84, 84, 4), 512),
        (AtariDeepTorso(), (72, 96, 3), 256),
    ],
)
def test_torso_shapes(torso, obs_shape, feat):
    params = torso.init(jax.random.key(0), jnp.zeros((2, *obs_shape)))
    out = torso.apply(params, jnp.zeros((2, *obs_shape)))
    chex.assert_shape(out, (2, feat))


def test_torso_uint8_pixels_scaled():
    torso = AtariShallowTorso()
    obs = np.zeros((1, 84, 84, 4), np.uint8)
    params = torso.init(jax.random.key(0), jnp.asarray(obs))
    a = torso.apply(params, jnp.asarray(obs))
    b = torso.apply(params, jnp.full((1, 84, 84, 4), 255, jnp.uint8))
    # 0 and 255 inputs must differ — i.e. scaling happened, not a uint8 cast.
    assert not np.allclose(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("use_lstm", [False, True])
def test_step_and_unroll_shapes(use_lstm):
    T, B, A = 5, 3, 4
    agent, params = _make_agent(use_lstm)
    state = agent.initial_state(B)
    out = agent.step(
        params,
        jax.random.key(1),
        jnp.zeros((B, 8)),
        jnp.ones((B,), jnp.bool_),
        state,
    )
    chex.assert_shape(out.action, (B,))
    chex.assert_shape(out.policy_logits, (B, A))

    net_out, final_state = agent.unroll(
        params,
        jnp.zeros((T, B, 8)),
        jnp.zeros((T, B), jnp.bool_),
        state,
    )
    chex.assert_shape(net_out.policy_logits, (T, B, A))
    chex.assert_shape(net_out.values, (T, B, 1))
    if use_lstm:
        chex.assert_shape(final_state[0], (B, 12))


@pytest.mark.parametrize("use_lstm", [False, True])
def test_unroll_matches_sequential_steps(use_lstm):
    """Learner unroll must reproduce the actor's step-by-step forward pass."""
    T, B = 6, 2
    agent, params = _make_agent(use_lstm)
    rng = np.random.default_rng(0)
    obs = jnp.asarray(rng.normal(size=(T, B, 8)), jnp.float32)
    first = jnp.asarray(rng.uniform(size=(T, B)) < 0.3)

    state = agent.initial_state(B)
    step_logits = []
    for t in range(T):
        out, state = agent.net.apply(
            params, obs[t], first[t], state, unroll=False
        )
        step_logits.append(out.policy_logits)
    step_logits = jnp.stack(step_logits)

    net_out, _ = agent.unroll(params, obs, first, agent.initial_state(B))
    np.testing.assert_allclose(
        net_out.policy_logits, step_logits, rtol=1e-5, atol=1e-5
    )


def test_lstm_reset_equals_fresh_state():
    """A `first` flag mid-unroll must reproduce a fresh-state unroll from
    that point (hk.ResetCore semantics)."""
    T, B = 8, 2
    k = 5  # episode boundary
    agent, params = _make_agent(use_lstm=True)
    rng = np.random.default_rng(1)
    obs = jnp.asarray(rng.normal(size=(T, B, 8)), jnp.float32)
    first = np.zeros((T, B), bool)
    first[k] = True

    net_out, _ = agent.unroll(
        params, obs, jnp.asarray(first), agent.initial_state(B)
    )
    # Run the suffix alone from a fresh state with first=True at its start.
    suffix_first = np.zeros((T - k, B), bool)
    suffix_first[0] = True
    suffix_out, _ = agent.unroll(
        params, obs[k:], jnp.asarray(suffix_first), agent.initial_state(B)
    )
    np.testing.assert_allclose(
        net_out.policy_logits[k:],
        suffix_out.policy_logits,
        rtol=1e-5,
        atol=1e-5,
    )


def test_lstm_state_propagates_without_reset():
    """Without first flags, different prior states give different outputs."""
    B = 3
    agent, params = _make_agent(use_lstm=True)
    obs = jnp.ones((B, 8))
    no_first = jnp.zeros((B,), jnp.bool_)
    zero_state = agent.initial_state(B)
    out0, state1 = agent.net.apply(params, obs, no_first, zero_state)
    out1, _ = agent.net.apply(params, obs, no_first, state1)
    assert not np.allclose(
        np.asarray(out0.policy_logits), np.asarray(out1.policy_logits)
    )


def test_multi_value_head():
    net = ImpalaNet(
        num_actions=3,
        torso=MLPTorso(hidden_sizes=(8,)),
        num_values=30,  # DMLab-30-style multi-task head
    )
    params = net.init(
        jax.random.key(0),
        jnp.zeros((1, 4)),
        jnp.ones((1,), jnp.bool_),
        (),
    )
    out, _ = net.apply(params, jnp.zeros((2, 4)), jnp.zeros((2,), jnp.bool_), ())
    chex.assert_shape(out.values, (2, 30))
    # PopArt needs a stable value-head path.
    assert "value_head" in params["params"]


def test_sampled_actions_follow_logits():
    """Greedy check: with a strongly peaked policy, samples match argmax."""
    agent, params = _make_agent(use_lstm=False)
    # Make the policy near-deterministic by scaling the head kernel.
    params = jax.tree.map(lambda x: x, params)  # copy
    out = agent.step(
        params,
        jax.random.key(2),
        jnp.asarray(np.random.default_rng(3).normal(size=(512, 8)), jnp.float32),
        jnp.ones((512,), jnp.bool_),
        agent.initial_state(512),
    )
    assert out.action.min() >= 0 and out.action.max() < 4


class TestBF16Compute:
    def test_bf16_torso_outputs_f32_and_matches_f32_loosely(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from torched_impala_tpu.models import (
            Agent,
            AtariShallowTorso,
            ImpalaNet,
        )

        rng = np.random.default_rng(0)
        obs = rng.integers(0, 256, size=(2, 3, 84, 84, 4)).astype(np.uint8)
        first = np.zeros((2, 3), np.bool_)

        outs = {}
        for name, dtype in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
            agent = Agent(
                ImpalaNet(
                    num_actions=5,
                    torso=AtariShallowTorso(dtype=dtype),
                    use_lstm=True,
                    lstm_size=16,
                )
            )
            params = agent.init_params(
                jax.random.key(0), jnp.zeros((84, 84, 4), jnp.uint8)
            )
            net_out, _ = agent.unroll(
                params, jnp.asarray(obs), jnp.asarray(first),
                agent.initial_state(3),
            )
            # Heads and loss math must stay float32 regardless of torso dtype.
            assert net_out.policy_logits.dtype == jnp.float32
            assert net_out.values.dtype == jnp.float32
            outs[name] = net_out

        # Same init (same seed/param shapes+dtypes): bf16 compute should
        # track f32 within bf16's ~3 decimal digits.
        np.testing.assert_allclose(
            np.asarray(outs["f32"].policy_logits),
            np.asarray(outs["bf16"].policy_logits),
            rtol=0.1,
            atol=0.1,
        )

    def test_bf16_params_stay_float32(self):
        import jax
        import jax.numpy as jnp

        from torched_impala_tpu import configs

        cfg = configs.REGISTRY["pong"]
        assert cfg.compute_dtype == "bfloat16"
        agent = configs.make_agent(cfg)
        params = agent.init_params(
            jax.random.key(0), jnp.asarray(configs.example_obs(cfg))
        )
        for leaf in jax.tree.leaves(params):
            assert leaf.dtype == jnp.float32, leaf.dtype


@pytest.mark.slow
def test_remat_torso_is_parameter_and_output_transparent():
    """configs.remat_torso wraps the torso in nn.remat: the param tree,
    outputs, AND gradients must be identical to the unwrapped net (so
    checkpoints interchange and the only difference is backward-pass
    memory) — the MFU-campaign lever for HBM-bound batch sizes."""
    import dataclasses

    from torched_impala_tpu import configs

    cfg = dataclasses.replace(
        configs.REGISTRY["breakout"], remat_torso=False
    )
    cfg_r = dataclasses.replace(cfg, remat_torso=True)
    T, B = 3, 2
    obs = jnp.asarray(
        np.random.default_rng(0).integers(
            0, 256, size=(T, B, 84, 84, 4), dtype=np.uint8
        )
    )
    first = jnp.zeros((T, B), bool)

    outs, grads = [], []
    for c in (cfg, cfg_r):
        agent = configs.make_agent(c)
        params = agent.init_params(
            jax.random.key(0), jnp.zeros((84, 84, 4), jnp.uint8)
        )
        state = agent.initial_state(B)

        def loss(p):
            out, _ = agent.net.apply(p, obs, first, state, unroll=True)
            return (
                jnp.sum(jnp.sin(out.policy_logits))
                + jnp.sum(jnp.sin(out.values))
            )

        outs.append(loss(params))
        grads.append(jax.grad(loss)(params))

    # Identical param TREE STRUCTURE (checkpoint compatibility)...
    assert jax.tree_util.tree_structure(
        grads[0]
    ) == jax.tree_util.tree_structure(grads[1])
    # ...identical loss and gradients.
    np.testing.assert_allclose(
        float(outs[0]), float(outs[1]), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        grads[0],
        grads[1],
    )


def test_space_to_depth_conv_matches_plain_conv_all_paddings():
    """_FirstPixelConv's space-to-depth rewrite (both padding conventions,
    including the SAME branch no shipped torso uses) must equal the plain
    strided conv on the same params — f32-exact up to accumulation order."""
    import numpy as np

    from torched_impala_tpu.models.torsos import _FirstPixelConv

    rng = np.random.default_rng(7)
    for h, w, k, s, padding in (
        (84, 84, 8, 4, "VALID"),
        (84, 84, 8, 4, "SAME"),
        (83, 85, 8, 4, "SAME"),  # odd sizes: asymmetric low/high pad
        (36, 40, 6, 3, "SAME"),
        (36, 40, 4, 2, "VALID"),
    ):
        obs = jnp.asarray(
            rng.integers(0, 256, size=(3, h, w, 4), dtype=np.uint8)
        )
        mod = _FirstPixelConv(16, (k, k), strides=(s, s), padding=padding)
        params = mod.init(jax.random.key(1), obs)
        out_s2d = mod.apply(params, obs)
        # Reference: plain strided lax conv on the same (scaled) kernel.
        kernel = params["params"]["kernel"] * (1.0 / 255.0)
        ref = jax.lax.conv_general_dilated(
            obs.astype(jnp.float32),
            kernel,
            (s, s),
            padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params["params"]["bias"]
        np.testing.assert_allclose(
            np.asarray(out_s2d), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"{h}x{w} k{k}s{s} {padding}",
        )


def test_pixel_rescale_fold_matches_explicit_division():
    """The first-conv 1/255 fold (torsos._FirstPixelConv — the kernel-side
    fold, plus space-to-depth on the shallow torso's strided first conv)
    must be numerically the same transform as dividing the input: feeding
    uint8 through the fold equals feeding the explicitly normalized float
    input through the same params."""
    import numpy as np

    from torched_impala_tpu.models import AtariDeepTorso, AtariShallowTorso

    rng = np.random.default_rng(3)
    obs_u8 = jnp.asarray(
        rng.integers(0, 256, size=(6, 84, 84, 4), dtype=np.uint8)
    )
    obs_f32 = obs_u8.astype(jnp.float32) / 255.0
    # Both dtypes tight-ish: the kernel-side fold keeps activations in
    # the normalized range, so bf16 differs only by normal rounding
    # accumulated through the stack (the r4 output-side fold ran the
    # first conv on 0..255 inputs and needed 0.08-loose pinning here).
    # bf16 atol is 0.06, not 0.03: a handful of pre-activation values
    # land within one bf16 ulp of zero, and the rounding difference
    # between the two input paths flips them across the ReLU threshold
    # (~1/1500 elements at |diff| ~ 0.031-0.05 in practice).
    for dtype, rtol, atol in (
        (jnp.float32, 1e-4, 1e-4),
        (jnp.bfloat16, 0.06, 0.06),
    ):
        for cls in (AtariShallowTorso, AtariDeepTorso):
            torso = cls(dtype=dtype)
            params = torso.init(jax.random.key(0), obs_u8)
            out_fold = torso.apply(params, obs_u8)  # uint8 -> folded
            out_ref = torso.apply(params, obs_f32)  # float -> plain
            np.testing.assert_allclose(
                np.asarray(out_fold, np.float32),
                np.asarray(out_ref, np.float32),
                rtol=rtol,
                atol=atol,
            )
