"""Anakin on-device actor-learner: learning, determinism, and the sharded
(DP) path on the virtual CPU mesh.

The whole iteration is one XLA program (runtime/anakin.py), so these tests
double as compile checks for the fused rollout+train graph.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torched_impala_tpu.envs import JaxCartPole, JaxCatch
from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
from torched_impala_tpu.ops import ImpalaLossConfig
from torched_impala_tpu.parallel import make_mesh
from torched_impala_tpu.runtime import AnakinConfig, AnakinRunner


def _agent(num_actions):
    return Agent(
        ImpalaNet(
            num_actions=num_actions, torso=MLPTorso(hidden_sizes=(32,))
        )
    )


def _runner(env, num_actions, *, E=16, T=10, lr=3e-3, mesh=None, seed=0):
    return AnakinRunner(
        agent=_agent(num_actions),
        env=env,
        optimizer=optax.rmsprop(lr, decay=0.99, eps=1e-7),
        config=AnakinConfig(
            num_envs=E,
            unroll_length=T,
            loss=ImpalaLossConfig(reduction="mean"),
        ),
        rng=jax.random.key(seed),
        mesh=mesh,
    )


def test_catch_learns_on_device():
    """Catch return rises from ~random (<=0) to clearly positive."""
    runner = _runner(JaxCatch(), 3, E=32, T=9, lr=5e-3)
    early = runner.run(30)
    late = runner.run(300)
    assert np.isfinite(late["total_loss"])
    assert late["episode_return_mean"] > max(
        0.3, early["episode_return_mean"] + 0.3
    ), (early["episode_return_mean"], late["episode_return_mean"])


def test_cartpole_smoke_runs_and_counts_frames():
    runner = _runner(JaxCartPole(), 2, E=8, T=16)
    logs = runner.run(5)
    assert np.isfinite(logs["total_loss"])
    assert runner.num_frames == 5 * 8 * 16
    assert logs["frames_per_sec"] > 0


def test_deterministic_across_runners():
    a = _runner(JaxCatch(), 3, seed=7)
    b = _runner(JaxCatch(), 3, seed=7)
    la = [float(a.step()["total_loss"]) for _ in range(3)]
    lb = [float(b.step()["total_loss"]) for _ in range(3)]
    np.testing.assert_allclose(la, lb, rtol=0, atol=0)


def test_sharded_matches_single_device():
    """Same seed: the 8-way DP runner computes the same math as the
    single-device one (per-env RNG is fold_in(key, global index), so the
    stream is placement-invariant; only reduction order differs)."""
    mesh = make_mesh(num_data=8, devices=jax.devices("cpu")[:8])
    single = _runner(JaxCatch(), 3, E=16, T=9, seed=11)
    sharded = _runner(JaxCatch(), 3, E=16, T=9, seed=11, mesh=mesh)
    for _ in range(3):
        ls = single.step()
        lm = sharded.step()
    np.testing.assert_allclose(
        float(ls["total_loss"]), float(lm["total_loss"]), rtol=2e-4
    )
    for leaf in jax.tree.leaves(sharded.params):
        assert leaf.sharding.is_fully_replicated
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(single.params)[0]),
        np.asarray(jax.tree.leaves(sharded.params)[0]),
        rtol=2e-4,
        atol=1e-5,
    )


def test_lstm_core_compiles_on_device_loop():
    """The recurrent carry threads through the fused rollout+train program."""
    agent = Agent(
        ImpalaNet(
            num_actions=3,
            torso=MLPTorso(hidden_sizes=(16,)),
            use_lstm=True,
            lstm_size=8,
        )
    )
    runner = AnakinRunner(
        agent=agent,
        env=JaxCatch(),
        optimizer=optax.sgd(1e-3),
        config=AnakinConfig(num_envs=4, unroll_length=6),
        rng=jax.random.key(0),
    )
    logs = runner.run(3)
    assert np.isfinite(logs["total_loss"])


def test_conv_policy_learns_pixels_on_device():
    """The on-device path at pixel shapes: a Nature-CNN policy learns the
    quadrant->action signal (JaxPixelSignal), i.e. the conv pipeline works
    end-to-end INSIDE the fused rollout+train program."""
    from torched_impala_tpu.envs import JaxPixelSignal
    from torched_impala_tpu.models import AtariShallowTorso

    env = JaxPixelSignal(size=36, channels=1, episode_len=10)
    runner = AnakinRunner(
        agent=Agent(
            ImpalaNet(num_actions=4, torso=AtariShallowTorso())
        ),
        env=env,
        optimizer=optax.rmsprop(1e-3, decay=0.99, eps=1e-7),
        config=AnakinConfig(
            num_envs=16,
            unroll_length=10,
            loss=ImpalaLossConfig(reduction="mean"),
        ),
        rng=jax.random.key(0),
    )
    early = runner.run(10)
    late = runner.run(120)
    # Random policy averages episode_len/4 = 2.5; reading the pixels
    # approaches 10 (the cap — keep the relative bound satisfiable even
    # if early learning is fast).
    assert late["episode_return_mean"] > max(
        4.0, min(early["episode_return_mean"] * 1.3, 8.0)
    ), (early["episode_return_mean"], late["episode_return_mean"])


def test_sharded_conv_pixels_runs():
    """The realistic sharded shape: conv policy + pixel env batch over the
    8-device mesh — compiles, executes, params stay replicated."""
    from torched_impala_tpu.envs import JaxPixelSignal
    from torched_impala_tpu.models import AtariShallowTorso

    mesh = make_mesh(num_data=8, devices=jax.devices("cpu")[:8])
    runner = AnakinRunner(
        agent=Agent(
            ImpalaNet(num_actions=4, torso=AtariShallowTorso())
        ),
        env=JaxPixelSignal(size=36, channels=1, episode_len=6),
        optimizer=optax.sgd(1e-3),
        config=AnakinConfig(num_envs=8, unroll_length=4),
        rng=jax.random.key(0),
        mesh=mesh,
    )
    logs = runner.run(2)
    assert np.isfinite(logs["total_loss"])
    for leaf in jax.tree.leaves(runner.params):
        assert leaf.sharding.is_fully_replicated


# ---- fused multi-update dispatch (updates_per_dispatch > 1) ------------


def _runner_n(n, *, seed=3, E=16, T=9, mesh=None):
    return AnakinRunner(
        agent=_agent(3),
        env=JaxCatch(),
        optimizer=optax.sgd(1e-2),
        config=AnakinConfig(
            num_envs=E,
            unroll_length=T,
            loss=ImpalaLossConfig(reduction="mean"),
            updates_per_dispatch=n,
        ),
        rng=jax.random.key(seed),
        mesh=mesh,
    )


def test_fused_updates_match_sequential():
    """One N=2 fused dispatch == two sequential dispatches: same params,
    same counters, and episode stats aggregated over both windows."""
    seq, fused = _runner_n(1), _runner_n(2)
    l1, l2 = seq.step(), seq.step()
    lf = fused.step()

    assert seq.num_steps == fused.num_steps == 2
    assert seq.num_frames == fused.num_frames
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        jax.tree.map(np.asarray, seq.params),
        jax.tree.map(np.asarray, fused.params),
    )
    # Episode stats aggregate across the two windows.
    f1, f2 = float(l1["episodes_finished"]), float(l2["episodes_finished"])
    assert float(lf["episodes_finished"]) == pytest.approx(f1 + f2)
    assert f1 + f2 > 0, "test needs completed episodes (T=9 Catch)"
    want = (
        float(l1["episode_return_mean"]) * f1
        + float(l2["episode_return_mean"]) * f2
    ) / (f1 + f2)
    assert float(lf["episode_return_mean"]) == pytest.approx(
        want, rel=1e-5
    )
    # Non-episode scalars are the LAST window's.
    np.testing.assert_allclose(
        float(lf["total_loss"]), float(l2["total_loss"]), rtol=1e-5
    )


def test_fused_updates_sharded():
    """Fused N=2 over the 8-device data mesh runs and matches the fused
    single-device run."""
    mesh = make_mesh(num_data=8)
    single, sharded = _runner_n(2, E=16), _runner_n(2, E=16, mesh=mesh)
    ls, lm = single.step(), sharded.step()
    np.testing.assert_allclose(
        float(ls["total_loss"]), float(lm["total_loss"]), rtol=1e-4
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        jax.tree.map(np.asarray, single.params),
        jax.tree.map(np.asarray, sharded.params),
    )
    for leaf in jax.tree.leaves(sharded.params):
        assert leaf.sharding.is_fully_replicated


def test_tensor_parallel_matches_single_device():
    """(2, 4) data x model mesh: the fully on-device runner with weight
    matrices Megatron-column-sharded (parallel.model_shardings, same rule
    as the Learner) computes the same POLICY as the single-device runner
    — compared at the distribution level, with at least one weight
    genuinely sharded and a checkpoint roundtrip landing leaves back on
    their shards.

    Why distributions and not losses/params (PR 11 root cause): TP's
    column-sharded matmuls reduce in a different order, and the ~1-ulp
    logit noise occasionally flips a categorical SAMPLE inside the fused
    rollout; trajectories then diverge chaotically, so sampled-action
    quantities (pg/total loss, raw param values) are NOT comparable
    across layouts. The layout-invariant contracts are: the sharded
    forward pass reproduces the single-device action distribution on a
    probe batch to f32 tolerance, and the policy entropy trace stays
    matched through training."""
    mesh = make_mesh(
        num_data=2, num_model=4, devices=jax.devices("cpu")[:8]
    )
    single = _runner(JaxCatch(), 3, E=16, T=9, seed=11)
    tp = _runner(JaxCatch(), 3, E=16, T=9, seed=11, mesh=mesh)

    # Identical inits: the two runners start from byte-equal params, so
    # any forward-parity gap below is the TP compute path itself.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.tree.map(np.asarray, single.params),
        jax.tree.map(np.asarray, tp.params),
    )

    env = JaxCatch()
    probe_keys = jax.random.split(jax.random.key(123), 32)
    probe_obs = np.asarray(
        jax.vmap(lambda k: env.observe(env.reset(k)))(probe_keys)
    )
    agent = _agent(3)

    def policy_probs(params):
        out = agent.step(
            params,
            jax.random.key(0),
            probe_obs,
            np.ones((32,), np.bool_),
            agent.initial_state(32),
        )
        logits = np.asarray(out.policy_logits, np.float64)
        z = np.exp(logits - logits.max(-1, keepdims=True))
        return z / z.sum(-1, keepdims=True)

    # Forward parity: the Megatron-sharded forward reproduces the
    # single-device distribution (only reduction order may differ).
    np.testing.assert_allclose(
        policy_probs(single.params),
        policy_probs(tp.params),
        rtol=1e-5,
        atol=1e-6,
    )

    for _ in range(3):
        ls = single.step()
        lt = tp.step()
        np.testing.assert_allclose(
            float(ls["entropy"]), float(lt["entropy"]), atol=1.5e-2
        )

    sharded_leaves = [
        leaf
        for leaf in jax.tree.leaves(tp.params)
        if not leaf.sharding.is_fully_replicated
    ]
    assert sharded_leaves, "TP produced no sharded anakin weights"
    state = tp.get_state()
    tp.set_state(state)
    again = [
        leaf
        for leaf in jax.tree.leaves(tp.params)
        if not leaf.sharding.is_fully_replicated
    ]
    assert len(again) == len(sharded_leaves)
