"""Ulysses all-to-all SP attention vs dense oracle (and vs the ring).

Same contract as tests/test_ring_attention.py: the op must be EXACT.
Checked across device counts, causal/full, gradients, and agreement with
the ring implementation on identical inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torched_impala_tpu.parallel.ring_attention import (
    ring_attention_sharded,
    seq_mesh,
)
from torched_impala_tpu.parallel.ulysses import ulysses_attention_sharded

from attention_oracle import dense_attention, make_segments


def _qkv(rng, T, B=2, H=4, Dh=8):
    return tuple(
        jnp.asarray(rng.normal(size=(T, B, H, Dh)), jnp.float32)
        for _ in range(3)
    )


class TestEquivalence:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("n_dev", [2, 4])
    def test_matches_dense(self, causal, n_dev):
        rng = np.random.default_rng(0)
        T = n_dev * 5
        q, k, v = _qkv(rng, T)  # H=4 divisible by n_dev
        mesh = seq_mesh(n_dev)
        out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
        ref = dense_attention(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    @pytest.mark.slow
    def test_matches_ring(self):
        rng = np.random.default_rng(3)
        q, k, v = _qkv(rng, 16, H=8)
        mesh = seq_mesh(4)
        ul = ulysses_attention_sharded(q, k, v, mesh, causal=True)
        ring = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(
            np.asarray(ul), np.asarray(ring), rtol=2e-5, atol=2e-6
        )

    def test_head_divisibility_enforced(self):
        rng = np.random.default_rng(4)
        q, k, v = _qkv(rng, 8, H=3)  # 3 heads, 2 devices
        mesh = seq_mesh(2)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(q, k, v, mesh)

    @pytest.mark.slow  # 37 s: bwd equivalence; fwd stays quick-gated + dryrun program 3
    def test_gradients_match_dense(self):
        rng = np.random.default_rng(5)
        q, k, v = _qkv(rng, 8)
        mesh = seq_mesh(2)

        def loss_ul(q, k, v):
            return jnp.sum(
                ulysses_attention_sharded(q, k, v, mesh, causal=True) ** 2
            )

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, True) ** 2)

        g_ul = jax.grad(loss_ul, argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ul, g_d):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    @pytest.mark.slow
    def test_segment_ids_match_dense_and_ring(self):
        """Segment (episode-boundary) masking: Ulysses == dense oracle ==
        ring on the same segmented inputs."""
        rng = np.random.default_rng(21)
        T = 16
        q, k, v = _qkv(rng, T)
        seg = make_segments(rng, T, 2)
        mesh = seq_mesh(4)
        ul = ulysses_attention_sharded(
            q, k, v, mesh, causal=True, segment_ids=seg
        )
        ref = dense_attention(q, k, v, True, segment_ids=seg)
        ring = ring_attention_sharded(
            q, k, v, mesh, causal=True, segment_ids=seg
        )
        np.testing.assert_allclose(
            np.asarray(ul), np.asarray(ref), rtol=2e-5, atol=2e-6
        )
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(ref), rtol=2e-4, atol=2e-5
        )

    @pytest.mark.slow
    def test_prefix_cache_matches_dense_and_ring(self):
        """KV-cache prefix under Ulysses: each head group attends its
        slice of the replicated prefix; result == dense oracle == ring."""
        rng = np.random.default_rng(23)
        T, B, H, Dh, S = 16, 2, 4, 8, 5
        q, k, v = _qkv(rng, T)
        seg = make_segments(rng, T, B)
        pk = jnp.asarray(rng.normal(size=(S, B, H, Dh)), jnp.float32)
        pv = jnp.asarray(rng.normal(size=(S, B, H, Dh)), jnp.float32)
        pseg_np = np.full((S, B), -1, np.int32)
        pseg_np[2:] = np.asarray(seg)[0]
        pseg = jnp.asarray(pseg_np)
        mesh = seq_mesh(4)
        kw = dict(causal=True, segment_ids=seg,
                  prefix_k=pk, prefix_v=pv, prefix_seg=pseg)
        ul = ulysses_attention_sharded(q, k, v, mesh, **kw)
        ring = ring_attention_sharded(q, k, v, mesh, **kw)
        ref = dense_attention(q, k, v, True, segment_ids=seg,
                              prefix_k=pk, prefix_v=pv, prefix_seg=pseg)
        np.testing.assert_allclose(
            np.asarray(ul), np.asarray(ref), rtol=2e-5, atol=2e-6
        )
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(ref), rtol=2e-4, atol=2e-5
        )
