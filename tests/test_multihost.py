"""Multi-host learner test: 2 real OS processes, one global mesh.

SURVEY.md §5 item 5 taken one step further: not just 8 virtual devices in
one process, but jax.distributed across TWO processes (4 virtual CPU
devices each) — the actual multi-controller mechanism a v5e-16 pod uses,
exercised without a pod. Each process contributes half the global batch via
`multihost.place_batch`; the cross-process gradient all-reduce must produce
the identical loss on both.
"""

import os
import pathlib
import pytest
import socket
import subprocess
import sys

import numpy as np

WORKER = str(pathlib.Path(__file__).parent / "multihost_learner_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_global_mesh_learner_step():
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(port)],
            cwd=str(pathlib.Path(WORKER).parent.parent),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    losses, loop_losses, seed_sets, fused_losses = [], [], [], []
    tp_losses, tp_sharded = [], []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert len(lines) == 1, out
        losses.append(float(lines[0].split("loss=")[1]))
        lines2 = [
            ln for ln in out.splitlines() if ln.startswith("RESULT2 ")
        ]
        assert len(lines2) == 1, out
        loop_losses.append(
            float(lines2[0].split("loss=")[1].split(" ")[0])
        )
        seed_sets.append(lines2[0].split("seeds=")[1])
        lines3 = [
            ln for ln in out.splitlines() if ln.startswith("RESULT3 ")
        ]
        assert len(lines3) == 1, out
        fused_losses.append(float(lines3[0].split("loss=")[1]))
        lines4 = [
            ln for ln in out.splitlines() if ln.startswith("RESULT4 ")
        ]
        assert len(lines4) == 1, out
        tp_losses.append(
            float(lines4[0].split("loss=")[1].split(" ")[0])
        )
        tp_sharded.append(int(lines4[0].split("sharded=")[1]))
    # One global batch, one SPMD program: both controllers see THE loss.
    assert np.isfinite(losses[0])
    assert losses[0] == losses[1]
    # Full train() loop: same global program, same loss on both
    # controllers — but DISTINCT host-local actor seed sets (the
    # duplicate-data fix).
    assert np.isfinite(loop_losses[0])
    assert loop_losses[0] == loop_losses[1]
    assert seed_sets[0] != seed_sets[1]
    # Fused dispatch (steps_per_dispatch=2): the [K, ...] superbatch
    # assembles across hosts and both controllers report THE same loss.
    assert np.isfinite(fused_losses[0])
    assert fused_losses[0] == fused_losses[1]
    # DP x TP (4x2 global mesh under jax.distributed): weights genuinely
    # model-sharded, same loss on both controllers, and — same batch, same
    # init — the loss matches the DP-only phase up to reduction-order
    # noise (layout choice cannot change the math).
    assert tp_sharded[0] > 0 and tp_sharded[0] == tp_sharded[1]
    assert np.isfinite(tp_losses[0])
    assert tp_losses[0] == tp_losses[1]
    np.testing.assert_allclose(tp_losses[0], losses[0], rtol=1e-5)


# --------------------------------------------------------------- ISSUE 18
# Pod-scale harness (runtime/distributed.py): process-count-agnostic
# training. These run REAL multi-process clusters (parallel/simhost.py)
# but stay tier-1: each launch is a handful of tiny CPU steps.


def _parity_spec(num_hosts: int):
    from torched_impala_tpu.runtime.distributed import DistSpec

    return DistSpec(
        num_hosts=num_hosts,
        devices_per_host=1,
        # The pytest process carries 8 virtual CPU devices; pin the data
        # axis so the solo arm shards B=4 legally. Axis size 1 vs 2 is
        # part of what parity proves: layout cannot change the math.
        num_data=1 if num_hosts == 1 else None,
        total_steps=6,
        batch_size=4,
        unroll_length=5,
        seed=2,
        mode="feed_parity",
    )


def test_feed_parity_one_vs_two_processes():
    """The tentpole's correctness gate: one spec, run as ONE controller
    and as TWO, must walk the same loss trajectory.

    mode="feed_parity" feeds trajectories that are pure functions of
    (step, global_slot), each host covering only its own slots — so the
    global batch per step is identical at both host counts and the only
    remaining difference is WHERE the rows live and how the gradient
    all-reduce sums them. rtol covers collective summation order."""
    from torched_impala_tpu.runtime import distributed

    # 1-process arm runs in THIS process (process_count() == 1): the
    # identical code path minus jax.distributed, which is the point.
    solo = distributed.run_feed_parity(_parity_spec(1))
    assert solo["process_count"] == 1
    assert len(solo["losses"]) == 6

    res = distributed.launch_cluster(_parity_spec(2), timeout=240)
    assert res.ok, res.describe()
    payloads = [h.results()[-1] for h in res.hosts]
    assert [p["process_count"] for p in payloads] == [2, 2]
    # Both controllers of one SPMD program report THE loss trajectory.
    assert payloads[0]["losses"] == payloads[1]["losses"]
    assert all(np.isfinite(x) for x in payloads[0]["losses"])
    np.testing.assert_allclose(
        payloads[0]["losses"], solo["losses"], rtol=1e-3
    )


def test_two_process_cluster_trains_end_to_end():
    """Full path on a 2-process pod: per-host actor fleets + env pools
    feed host-local shards, the learner steps the global batch, and both
    controllers agree on losses, publish version, and global frame
    accounting."""
    from torched_impala_tpu.runtime.distributed import (
        DistSpec,
        launch_cluster,
    )

    spec = DistSpec(
        num_hosts=2,
        devices_per_host=1,
        total_steps=4,
        batch_size=4,
        unroll_length=4,
        num_actors=1,
        envs_per_actor=2,
        seed=5,
    )
    res = launch_cluster(spec, timeout=240)
    assert res.ok, res.describe()
    payloads = [h.results()[-1] for h in res.hosts]
    # Global batch semantics: each host contributes B/N rows.
    assert sorted(p["local_batch_size"] for p in payloads) == [2, 2]
    assert [p["steps"] for p in payloads] == [4, 4]
    # num_frames counts GLOBAL frames (T * global_B per step) on every
    # host — frame budgets must not depend on which host reports.
    assert [p["num_frames"] for p in payloads] == [4 * 4 * 4, 4 * 4 * 4]
    assert payloads[0]["losses"] == payloads[1]["losses"]
    assert len(payloads[0]["losses"]) == 4
    assert all(np.isfinite(x) for x in payloads[0]["losses"])
    # Param publish fan-out agrees across hosts.
    versions = {p["publish_version"] for p in payloads}
    assert len(versions) == 1 and versions.pop() >= 1


def test_kill_host_chaos_recovery():
    """Satellite 1 end-to-end: SIGKILL a host mid-ring-commit, reap the
    pod, restart from the newest async checkpoint, finish the run."""
    import shutil
    import tempfile

    from torched_impala_tpu.runtime.distributed import (
        DistSpec,
        launch_with_recovery,
    )

    ckdir = tempfile.mkdtemp(prefix="mh_chaos_test_")
    try:
        spec = DistSpec(
            num_hosts=2,
            devices_per_host=1,
            total_steps=10,
            batch_size=4,
            unroll_length=4,
            num_actors=1,
            envs_per_actor=2,
            seed=11,
            learner_overrides={"traj_ring": True},
            checkpoint_dir=ckdir,
            checkpoint_interval=2,
            chaos=[{"kind": "kill_host", "at": 2}],
            chaos_host=1,
        )
        final, attempts = launch_with_recovery(
            spec, max_restarts=2, timeout=240
        )
        # The fault is real: the first attempt must actually die (host 1
        # by SIGKILL, host 0 reaped by the launcher)...
        assert not attempts[0].ok
        assert any(h.returncode != 0 for h in attempts[0].hosts)
        # ...and the restarted pod resumes from the checkpoint and
        # finishes every step.
        assert final.ok, final.describe()
        payloads = [h.results()[-1] for h in final.hosts]
        assert max(p["steps"] for p in payloads) == 10
        assert payloads[0]["losses"] == payloads[1]["losses"]
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)
