"""Multi-host learner test: 2 real OS processes, one global mesh.

SURVEY.md §5 item 5 taken one step further: not just 8 virtual devices in
one process, but jax.distributed across TWO processes (4 virtual CPU
devices each) — the actual multi-controller mechanism a v5e-16 pod uses,
exercised without a pod. Each process contributes half the global batch via
`multihost.place_batch`; the cross-process gradient all-reduce must produce
the identical loss on both.
"""

import os
import pathlib
import pytest
import socket
import subprocess
import sys

import numpy as np

WORKER = str(pathlib.Path(__file__).parent / "multihost_learner_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_global_mesh_learner_step():
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(port)],
            cwd=str(pathlib.Path(WORKER).parent.parent),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
        outs.append(out)

    losses, loop_losses, seed_sets, fused_losses = [], [], [], []
    tp_losses, tp_sharded = [], []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert len(lines) == 1, out
        losses.append(float(lines[0].split("loss=")[1]))
        lines2 = [
            ln for ln in out.splitlines() if ln.startswith("RESULT2 ")
        ]
        assert len(lines2) == 1, out
        loop_losses.append(
            float(lines2[0].split("loss=")[1].split(" ")[0])
        )
        seed_sets.append(lines2[0].split("seeds=")[1])
        lines3 = [
            ln for ln in out.splitlines() if ln.startswith("RESULT3 ")
        ]
        assert len(lines3) == 1, out
        fused_losses.append(float(lines3[0].split("loss=")[1]))
        lines4 = [
            ln for ln in out.splitlines() if ln.startswith("RESULT4 ")
        ]
        assert len(lines4) == 1, out
        tp_losses.append(
            float(lines4[0].split("loss=")[1].split(" ")[0])
        )
        tp_sharded.append(int(lines4[0].split("sharded=")[1]))
    # One global batch, one SPMD program: both controllers see THE loss.
    assert np.isfinite(losses[0])
    assert losses[0] == losses[1]
    # Full train() loop: same global program, same loss on both
    # controllers — but DISTINCT host-local actor seed sets (the
    # duplicate-data fix).
    assert np.isfinite(loop_losses[0])
    assert loop_losses[0] == loop_losses[1]
    assert seed_sets[0] != seed_sets[1]
    # Fused dispatch (steps_per_dispatch=2): the [K, ...] superbatch
    # assembles across hosts and both controllers report THE same loss.
    assert np.isfinite(fused_losses[0])
    assert fused_losses[0] == fused_losses[1]
    # DP x TP (4x2 global mesh under jax.distributed): weights genuinely
    # model-sharded, same loss on both controllers, and — same batch, same
    # init — the loss matches the DP-only phase up to reduction-order
    # noise (layout choice cannot change the math).
    assert tp_sharded[0] > 0 and tp_sharded[0] == tp_sharded[1]
    assert np.isfinite(tp_losses[0])
    assert tp_losses[0] == tp_losses[1]
    np.testing.assert_allclose(tp_losses[0], losses[0], rtol=1e-5)
