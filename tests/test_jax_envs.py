"""Pure-JAX envs: step-for-step parity with gymnasium CartPole-v1 and
Catch invariants. These envs back the on-device Anakin path
(runtime/anakin.py), so their dynamics must match the host envs exactly —
a config switched between host actors and Anakin should see the same MDP.
"""

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torched_impala_tpu.envs import JaxCartPole, JaxCatch


class TestJaxCartPole:
    def test_matches_gymnasium_step_for_step(self):
        env = JaxCartPole()
        gym_env = gymnasium.make("CartPole-v1").unwrapped
        gym_env.reset(seed=0)
        key = jax.random.key(0)
        state = env.reset(key)
        # Start both from the jax reset state.
        gym_env.state = np.asarray(env.observe(state), np.float64)
        step = jax.jit(env.step)
        rng = np.random.default_rng(1)
        for t in range(200):
            action = int(rng.integers(0, 2))
            state, reward, done = step(state, jnp.asarray(action), key)
            g_obs, g_reward, g_term, g_trunc, _ = gym_env.step(action)
            np.testing.assert_allclose(
                np.asarray(env.observe(state)), g_obs, rtol=1e-5, atol=1e-6
            )
            assert float(reward) == float(g_reward) == 1.0
            assert bool(done) == bool(g_term or g_trunc)
            if done:
                break
        assert t > 5, "episode ended implausibly early"

    def test_truncates_at_500_steps(self):
        env = JaxCartPole()
        from torched_impala_tpu.envs.jax_envs import CartPoleState

        # Stable physics, one step before the time limit.
        state = CartPoleState(
            physics=jnp.zeros((4,), jnp.float32),
            t=jnp.asarray(499, jnp.int32),
        )
        _, _, done = env.step(state, jnp.asarray(0), jax.random.key(0))
        assert bool(done)

    def test_vmap_shapes(self):
        env = JaxCartPole()
        keys = jax.random.split(jax.random.key(0), 7)
        state = jax.vmap(env.reset)(keys)
        assert jax.vmap(env.observe)(state).shape == (7, 4)
        actions = jnp.zeros((7,), jnp.int32)
        state, reward, done = jax.vmap(env.step)(state, actions, keys)
        assert jax.vmap(env.observe)(state).shape == (7, 4)
        assert reward.shape == (7,)
        assert done.shape == (7,)


class TestJaxCatch:
    def test_episode_length_and_catching(self):
        env = JaxCatch()
        key = jax.random.key(3)
        state = env.reset(key)
        assert env.observe(state).shape == (env.rows * env.cols,)
        # Perfect policy: walk the paddle toward the ball column.
        for t in range(env.rows - 1):
            dx = int(np.sign(int(state.ball_x) - int(state.paddle_x)))
            state, reward, done = env.step(state, jnp.asarray(dx + 1), key)
            if t < env.rows - 2:
                assert float(reward) == 0.0 and not bool(done)
        assert bool(done)
        assert float(reward) == 1.0  # paddle reachable from center

    def test_missing_gives_negative_reward(self):
        env = JaxCatch()
        key = jax.random.key(0)
        # Always move left: with the ball anywhere but the far-left path,
        # the paddle ends away from the ball.
        for seed in range(10):
            state = env.reset(jax.random.key(seed))
            if int(state.ball_x) == env.cols - 1:
                break
        else:
            pytest.skip("no right-column ball in 10 seeds")
        done = False
        while not done:
            state, reward, done = env.step(state, jnp.asarray(0), key)
        assert float(reward) == -1.0


def test_jax_env_through_host_actor_runtime():
    """The same pure-JAX MDP trains through the HOST actor runtime via the
    gym adapter (configs routes env_family='jax_*' there), completing the
    'switch runtimes, keep the MDP' story."""
    import optax

    from torched_impala_tpu import configs
    from torched_impala_tpu.ops import ImpalaLossConfig
    from torched_impala_tpu.runtime import LearnerConfig
    from torched_impala_tpu.runtime.loop import train

    cfg = configs.REGISTRY["catch_anakin"]
    seen = []
    result = train(
        agent=configs.make_agent(cfg),
        env_factory=configs.make_env_factory(cfg),
        example_obs=configs.example_obs(cfg),
        num_actors=2,
        learner_config=LearnerConfig(
            batch_size=2,
            unroll_length=6,
            loss=ImpalaLossConfig(reduction="mean"),
        ),
        optimizer=optax.sgd(1e-3),
        total_steps=2,
        logger=seen.append,
        log_every=1,
    )
    assert result.learner.num_steps == 2
    assert seen and np.isfinite(float(seen[-1]["total_loss"]))
