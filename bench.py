"""Benchmark: learner frames/sec/chip on the Atari Pong config.

Measures the steady-state throughput of the full jit-compiled learner train
step (unroll re-forward of the Nature-CNN policy, V-trace, loss, backward,
RMSProp update) on device-resident synthetic [T, B] Atari batches — the
"learner frames/sec/chip" half of the BASELINE.json:2 metric. Env stepping
and H2D are excluded here (they are host-side and scale with actor count);
the learner step is the TPU-bound hot loop this metric tracks.

Prints ONE JSON line. `vs_baseline` is value / 62_500: the reference has no
published numbers (BASELINE.md), so the yardstick is the north-star target of
1M env-frames/s on a v5e-16 (BASELINE.json:5) prorated to one chip
(1_000_000 / 16 = 62_500 frames/s/chip).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    from torched_impala_tpu.models import Agent, AtariShallowTorso, ImpalaNet
    from torched_impala_tpu.ops import ImpalaLossConfig
    from torched_impala_tpu.runtime import Learner, LearnerConfig

    T, B = 20, 256
    num_actions = 6  # Pong
    log(f"bench: backend={jax.default_backend()} T={T} B={B}")

    agent = Agent(
        ImpalaNet(
            num_actions=num_actions,
            # bf16 torso matches the pong preset (configs.py): conv FLOPs
            # on the MXU fast path, heads/loss in f32.
            torso=AtariShallowTorso(dtype=jnp.bfloat16),
        )
    )
    learner = Learner(
        agent=agent,
        optimizer=optax.rmsprop(6e-4, decay=0.99, eps=1e-7),
        config=LearnerConfig(
            batch_size=B,
            unroll_length=T,
            loss=ImpalaLossConfig(reduction="sum"),
            publish_interval=1_000_000,  # exclude host publication from timing
        ),
        example_obs=np.zeros((84, 84, 4), np.uint8),
        rng=jax.random.key(0),
    )

    rng = np.random.default_rng(0)
    arrays = (
        jnp.asarray(
            rng.integers(0, 256, size=(T + 1, B, 84, 84, 4), dtype=np.uint8)
        ),
        jnp.asarray(rng.uniform(size=(T + 1, B)) < 0.01),
        jnp.asarray(rng.integers(0, num_actions, size=(T, B), dtype=np.int32)),
        jnp.asarray(rng.normal(size=(T, B, num_actions)), jnp.float32),
        jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
        jnp.asarray((rng.uniform(size=(T, B)) > 0.01), jnp.float32),
        jnp.zeros((B,), jnp.int32),  # task ids (single-task)
        (),
    )
    arrays = jax.device_put(arrays)

    params, opt_state, pa = learner.params, learner.opt_state, ()
    # Warmup/compile.
    params, opt_state, pa, logs = learner._train_step(
        params, opt_state, pa, *arrays
    )
    jax.block_until_ready(logs)
    log(f"bench: compiled, total_loss={float(logs['total_loss']):.3f}")

    steps = 30
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, pa, logs = learner._train_step(
            params, opt_state, pa, *arrays
        )
    jax.block_until_ready(logs)
    dt = time.perf_counter() - t0

    frames_per_sec = T * B * steps / dt
    n_chips = max(1, len(jax.devices()))
    value = frames_per_sec / n_chips
    result = {
        "metric": "learner_frames_per_sec_per_chip_pong",
        "value": round(value, 1),
        "unit": "frames/s/chip",
        "vs_baseline": round(value / 62_500.0, 3),
    }
    log(
        f"bench: {steps} steps in {dt:.3f}s -> {frames_per_sec:,.0f} frames/s "
        f"on {n_chips} chip(s)"
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
