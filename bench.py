"""Benchmark: learner frames/sec/chip on the Atari Pong config.

Measures the steady-state throughput of the full jit-compiled learner train
step (unroll re-forward of the Nature-CNN policy, V-trace, loss, backward,
RMSProp update) on device-resident synthetic [T, B] Atari batches — the
"learner frames/sec/chip" half of the BASELINE.json:2 metric. Env stepping
and H2D are excluded here (they are host-side and scale with actor count);
the learner step is the TPU-bound hot loop this metric tracks.

Prints ONE JSON line. `vs_baseline` is value / 62_500: the reference has no
published numbers (BASELINE.md), so the yardstick is the north-star target of
1M env-frames/s on a v5e-16 (BASELINE.json:5) prorated to one chip
(1_000_000 / 16 = 62_500 frames/s/chip).

Hardened against this machine's documented traps (VERDICT round 1 weak #1):
- The TPU plugin env wiring DRIFTS between rounds: in round 1 a stray
  PYTHONPATH broke the axon plugin; in round 2 the plugin *lives on*
  PYTHONPATH (/root/.axon_site) and stripping it is what breaks TPU
  ("No jellyfish device found" / unknown backend 'axon'). So no single
  fixed env is trusted — a LADDER of candidate envs is probed in bounded
  subprocesses and the bench re-execs itself under the first one whose
  jax.devices() reports a real TPU.
- The axon tunnel can wedge machine-wide (jax.devices() hangs for hours) →
  every probe runs in a subprocess with a bounded timeout; if no candidate
  reaches a TPU, fall back to the CPU backend and label the JSON line with
  `"backend": "cpu"` + a note (a CPU number is not the TPU metric, but it is
  evidence the pipeline runs; the driver can tell them apart).
- Any unexpected exception still emits ONE parseable JSON line with an
  `error` key instead of a bare stack trace.
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))

PROBE_TIMEOUT_S = 150  # first axon contact can take ~30s; wedged = hours
_RESOLVED_MARKER = "_BENCH_TPU_RESOLVED"  # set after the probe ladder ran


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _history_append(
    section: str,
    metrics: dict,
    *,
    tiny: bool = False,
    direction: str = "higher",
    backend: str = "",
) -> None:
    """Append a section's headline numerics to BENCH_HISTORY.jsonl —
    the input of the regression gate (tools/perfgate.py, overridable
    via $BENCH_HISTORY_PATH). Tiny CI variants get a `tiny_` metric
    prefix so laptop smoke numbers never meet full-run budgets. Never
    raises: history is a side channel, not a bench dependency."""
    try:
        from tools.perfgate import append_history

        prefix = "tiny_" if tiny else ""
        for metric, value in metrics.items():
            if isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                append_history(
                    section,
                    prefix + metric,
                    float(value),
                    direction=direction,
                    backend=backend,
                )
    except Exception as e:
        log(f"bench: history append failed: {type(e).__name__}: {e}")


def _candidate_envs():
    """Env ladder, most-likely-to-work first: current env untouched, then
    JAX_PLATFORMS unset/auto, then explicit tpu, each also retried with
    PYTHONPATH stripped (the round-1 failure mode)."""
    base = dict(os.environ)
    for strip_pp in (False, True):
        for platforms in (base.get("JAX_PLATFORMS"), None, "tpu"):
            env = dict(base)
            if strip_pp:
                env.pop("PYTHONPATH", None)
            env.pop("JAX_PLATFORMS", None)
            if platforms:
                env["JAX_PLATFORMS"] = platforms
            desc = (
                f"JAX_PLATFORMS={platforms or '<unset>'}"
                f"{' PYTHONPATH-stripped' if strip_pp else ''}"
            )
            yield desc, env


def resolve_tpu_env():
    """Probe the ladder; return (tpu_ok, env_to_run_under)."""
    seen = set()
    for desc, env in _candidate_envs():
        key = (env.get("JAX_PLATFORMS"), env.get("PYTHONPATH"))
        if key in seen:
            continue
        seen.add(key)
        code = "import jax; print([d.platform for d in jax.devices()])"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                cwd=REPO,
                env=env,
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            log(f"bench: probe [{desc}] timed out after {PROBE_TIMEOUT_S}s")
            continue
        if proc.returncode != 0:
            log(f"bench: probe [{desc}] rc={proc.returncode}: {proc.stderr.strip().splitlines()[-1] if proc.stderr.strip() else ''}")
            continue
        if "'tpu'" in proc.stdout or "'axon'" in proc.stdout:
            log(f"bench: probe [{desc}] found TPU: {proc.stdout.strip()}")
            return True, env
        log(f"bench: probe [{desc}] no TPU (devices={proc.stdout.strip()})")
    return False, dict(os.environ)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--fast",
        action="store_true",
        help=(
            "5-minute capture mode: headline K=1 + fused dispatch + "
            "anakin_pixels locked best configs ONLY, with a hard 300s "
            "wall-clock alarm. Built for narrow tunnel-heal windows: the "
            "watcher runs this FIRST so the three most load-bearing "
            "numbers get banked even if the tunnel re-wedges before the "
            "full run finishes (VERDICT r3 item 1)."
        ),
    )
    p.add_argument(
        "--out",
        default=None,
        help=(
            "Path to write the (partial) result JSON after EVERY completed "
            "section (atomic tmp+rename). A bench killed mid-run still "
            "leaves every finished section's numbers on disk for the "
            "watcher to commit."
        ),
    )
    return p.parse_args(argv)


def main(args) -> None:
    if _RESOLVED_MARKER not in os.environ:
        tpu_ok, env = resolve_tpu_env()
        env[_RESOLVED_MARKER] = "tpu" if tpu_ok else "cpu"
        if tpu_ok and env.get("JAX_PLATFORMS"):
            # Expose a host CPU device alongside the TPU so actor-side policy
            # inference in the e2e bench can avoid per-step tunnel dispatch
            # (default backend stays the TPU plugin, listed first).
            if "cpu" not in env["JAX_PLATFORMS"]:
                env["JAX_PLATFORMS"] = env["JAX_PLATFORMS"] + ",cpu"
        os.execve(
            sys.executable,
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env,
        )
    tpu_ok = os.environ[_RESOLVED_MARKER] == "tpu"
    import jax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")
    try:
        # Persistent compilation cache: repeat bench runs (and driver
        # retries) skip the ~20-40s tunnelled compiles entirely.
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        log(f"bench: compilation cache unavailable: {e}")
    result = {
        "mode": "fast" if args.fast else "full",
        "partial": True,
        "sections_done": [],
    }

    def write_partial() -> None:
        """Atomically persist everything measured so far. Called after every
        section so a mid-run kill (tunnel re-wedge, SIGKILL, alarm) still
        leaves banked numbers for the watcher to commit."""
        if args.out is None:
            return
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, args.out)

    timed_out = False
    try:
        result.update(run_bench(jax, tpu_ok))
        result["sections_done"].append("headline")
    except Exception as e:
        # Even a failed headline must not lose later sections: record the
        # error under the primary keys the driver parses. A TimeoutError
        # means the wall-clock alarm fired (the alarm is now spent), so
        # every later section must be skipped, not run unguarded against
        # a possibly-wedged tunnel.
        if isinstance(e, TimeoutError):
            timed_out = True
        log(f"bench: headline failed: {type(e).__name__}: {e}")
        result.update(
            {
                "metric": "learner_frames_per_sec_per_chip_pong",
                "value": 0.0,
                "unit": "frames/s/chip",
                "vs_baseline": 0.0,
                "backend": jax.default_backend(),
                "error": f"{type(e).__name__}: {e}"[:300],
            }
        )
    write_partial()
    if "error" not in result and result.get("value", 0.0) > 0:
        _history_append(
            "headline",
            {result["metric"]: result["value"]},
            backend=result.get("backend", ""),
        )

    def section(key, fn, *, gate=True):
        """Extras must not kill the primary metric: failures become an
        `error` value under the section's key. Once the wall-clock alarm
        fires, every remaining section is skipped — after a timeout the
        tunnel is suspect, and the priority is emitting the JSON that
        already holds the completed sections."""
        nonlocal timed_out
        if not gate:
            return
        if timed_out:
            result[key] = {"skipped": "wall-clock limit already hit"}
            return
        try:
            result[key] = fn()
            result["sections_done"].append(key)
        except TimeoutError as e:
            timed_out = True
            log(f"bench: {key} hit the wall-clock limit: {e}")
            result[key] = {"error": f"TimeoutError: {e}"[:300]}
        except Exception as e:
            log(f"bench: {key} failed: {type(e).__name__}: {e}")
            result[key] = {"error": f"{type(e).__name__}: {e}"[:300]}
        write_partial()

    if args.fast:
        # Three most load-bearing unmeasured numbers, nothing else:
        # fused-dispatch ceiling (K=8 only — K=4 costs a second compile)
        # and the anakin_pixels locked configs (no sweep).
        section(
            "learner_fused",
            lambda: run_bench_fused(
                jax,
                ks=(8,),
                single_step_flops=result.get("train_step_gflops", 0.0) * 1e9,
                include_b64=False,  # fast mode: one compile only
            ),
            gate=tpu_ok,
        )
        _promote_fused(result)
        section(
            "anakin_pixels",
            lambda: run_bench_anakin_pixels(jax, fast=True),
            gate=tpu_ok,
        )
        # Stays partial if the alarm skipped anything OR the headline
        # itself errored: the watcher must not promote a capture whose
        # load-bearing number was never measured.
        result["partial"] = timed_out or "error" in result
        write_partial()
        print(json.dumps(result))
        return

    # Cheap, high-value TPU sections first so a slow e2e (host-bound on a
    # low-core box) hitting the wall-clock alarm can't starve them.
    section(
        "learner_fused",
        lambda: run_bench_fused(
            jax,
            single_step_flops=result.get("train_step_gflops", 0.0) * 1e9,
        ),
        gate=tpu_ok,
    )
    _promote_fused(result)
    section("learner_deep_breakout", lambda: run_bench_deep(jax), gate=tpu_ok)
    section("learner_scaling", lambda: run_bench_scaling(jax), gate=tpu_ok)
    # Compute-side MFU (ISSUE 16): bf16-vs-f32 step ratio + fused LSTM
    # ratio; ratios are same-backend quotients but budget-gated on TPU
    # only (bench runs tiny-prefixed on the CPU fallback). mfu_b1024
    # reuses the B=1024 headline MFU rather than recompiling it.
    section(
        "compute",
        lambda: run_bench_compute(
            jax,
            tiny=not tpu_ok,
            headline_mfu=result.get("mfu_estimate") if tpu_ok else None,
        ),
    )
    section("learner_remat", lambda: run_bench_remat(jax), gate=tpu_ok)
    section(
        "vtrace_pallas_vs_scan",
        lambda: run_vtrace_kernel_compare(jax),
        gate=tpu_ok,
    )
    section(
        "attention_pallas_vs_einsum",
        lambda: run_attention_kernel_compare(jax),
        gate=tpu_ok,
    )
    section("anakin_cartpole", lambda: run_bench_anakin(jax, tpu_ok))
    section("anakin_pixels", lambda: run_bench_anakin_pixels(jax), gate=tpu_ok)
    section("feeder_saturation", lambda: run_feeder_saturation(jax, tpu_ok))
    # Host-side section (no TPU involved): lockstep vs async ready-set
    # pool scheduling under straggler injection.
    section("env_pool", lambda: run_bench_env_pool(jax))
    # Host-side: telemetry registry overhead on the env-pool hot path
    # (ISSUE 2 acceptance: < 2% of env-pool steps/s with telemetry on).
    section("telemetry", lambda: run_bench_telemetry(jax))
    # Host-side: observability-plane exposition overhead + fan-in lane
    # latency (ISSUE 17 acceptance: scraping the OpenMetrics endpoint
    # costs <= 1% of env-pool steps/s). The overhead quotient is only
    # budget-meaningful on a TPU host with spare cores — on a 1-core CPU
    # VM the 20 Hz scraper thread steals a visible slice of the only
    # core, so CPU rows append tiny_-prefixed (same policy as compute).
    section("export", lambda: run_bench_export(jax, tiny=not tpu_ok))
    # In-step learning-health diagnostics overhead (ISSUE 19
    # acceptance: the health_* signals ride the existing train-step
    # dispatch for <= 1% of step time). Same tiny policy as export:
    # the on/off quotient is scheduler noise on a shared CPU core.
    section("health", lambda: run_bench_health(jax, tiny=not tpu_ok))
    # Host-side: flight-recorder overhead on the same hot path (ISSUE 4
    # acceptance: < 1% with tracing always on) + raw record-op ns.
    section("tracing", lambda: run_bench_tracing(jax))
    # Host-side: zero-copy trajectory ring vs the queue path (ISSUE 3
    # acceptance: host_stack span + per-unroll enqueue copy bytes drop,
    # batches bit-identical on fixed seeds).
    section("traj_ring", lambda: run_bench_traj_ring(jax))
    # Host-side: zero-copy feed path (ISSUE 13 acceptance: donated
    # stage-copy bytes = 0 with the superbatch ring past K=8, H2D
    # overlap fraction >= 0.8 steady state, fused V-trace+loss epilogue
    # step <= 0.9x the separate path at a loss-dominated shape).
    section("feed_path", lambda: run_bench_feed_path(jax))
    # Host-side: mesh-native feed variant (ISSUE 15 acceptance: zero
    # staged bytes under the 2-device data mesh with the donated ring,
    # per-shard placement <= 1.0x the stage-then-reshard hop).
    section("mesh_feed", lambda: run_bench_mesh_feed(jax))
    # Host-side: IMPACT replay on the ring (ISSUE 9 acceptance:
    # max_reuse=2 gives >= 1.8x SGD updates per env frame at equal env
    # throughput, per-update cost within a loose overhead bound).
    section("replay", lambda: run_bench_replay(jax))
    # Host-side: resilience chaos harness (ISSUE 5 acceptance: SIGKILL'd
    # env worker + crashed actor + crashed learner -> resume reaches the
    # target step count; async checkpoint overhead < 1%).
    section("chaos", lambda: run_bench_chaos(jax))
    # Host-side: simulated multi-host pod (ISSUE 18 acceptance: 2-host
    # weak-scaling efficiency >= 0.8 with env-paced feeds, all-reduce
    # overlap >= 0.8, kill_host chaos recovered to the return target).
    section("multihost", lambda: run_bench_multihost(jax))
    # Host-side: serving tier (ISSUE 6 acceptance: coalesced batching
    # >= 3x per-request actions/s at 64 clients, shadow traffic <= 5%
    # primary-wave latency, bf16 passes the greedy parity gate).
    section("serving", lambda: run_bench_serving(jax))
    # Host-side: fleet serving under open-loop load (ISSUE 14
    # acceptance: 2-replica fleet beats a single replica on goodput at
    # the same offered rate and p99 SLO; mid-wave replica kill absorbed
    # by router failover with zero failed requests).
    section("loadgen", lambda: run_bench_loadgen(jax))
    # Host-side: closed-loop control plane (ISSUE 12 acceptance:
    # controller-on >= static defaults on the standing-straggler pool
    # scenario and the serving burst scenario).
    section("control", lambda: run_bench_control(jax))
    section("e2e_components", lambda: run_e2e_components(jax))
    for mode in ("thread", "process"):
        section(f"e2e_{mode}", lambda mode=mode: run_e2e(jax, tpu_ok, mode))
    section("stack_reuse_compare", run_stack_reuse_compare)
    # Per-core host-path product answer (VERDICT r4 missing #4): combine
    # the integrated CPU drain (ring OFF — aliasing) with the ring +
    # simulated-H2D arm (the path a copying-H2D production host runs)
    # into one self-describing verdict against the 1.85 GB/s/chip bar.
    feeder = result.get("feeder_saturation", {})
    srcmp = result.get("stack_reuse_compare", {})
    required = None
    ring_arm = None
    if isinstance(feeder, dict):
        required = feeder.get("required_GBps_per_chip_62500fps")
    ring_arm_shape = None
    if isinstance(srcmp, dict):
        # Worst case across measured shapes (self-described below).
        arms = [
            (v["reuse_plus_sim_h2d_GBps"], k)
            for k, v in srcmp.items()
            if isinstance(v, dict) and "reuse_plus_sim_h2d_GBps" in v
        ]
        if arms:
            ring_arm, ring_arm_shape = min(arms)
    if required and ring_arm:
        result["host_path_ceiling"] = {
            "required_GBps_per_chip": required,
            "ring_stack_plus_sim_h2d_GBps_one_core": ring_arm,
            "measured_at_shape": ring_arm_shape,  # min across shapes
            "cores_per_chip_required": round(required / ring_arm, 2),
            "note": (
                "ring+sim-H2D arm = queue->ring-stack->copying transfer "
                "on ONE core; the integrated drain_cpu_* rows lower-bound "
                "it (CPU device_put aliasing disables the ring there)"
            ),
        }
    # Stays partial if the alarm skipped anything OR the headline errored:
    # tunnel_watch.sh promotes only `"partial": false` runs to
    # docs/evidence/BENCH_live.json and stops watching, so a capture missing its
    # load-bearing number must never qualify. (Per-SECTION errors don't
    # block promotion — section isolation is by design, e.g. an OOM arm
    # of the remat quadrant.)
    result["partial"] = timed_out or "error" in result
    write_partial()
    print(json.dumps(result))


def _promote_fused(result: dict) -> None:
    """`value` stays the K=1 single-dispatch metric so the number means the
    same thing in every round's record (ADVICE r2); the fused-dispatch
    product feature (steps_per_dispatch) is reported alongside under its
    own keys when it wins."""
    fused = result.get("learner_fused")
    if not isinstance(fused, dict):
        return
    best_k, best_fps = max(
        (
            (k, v)
            for k, v in fused.items()
            if isinstance(v, (int, float)) and "_" not in k
        ),
        key=lambda kv: kv[1],
        default=(None, 0.0),
    )
    if best_k is not None and best_fps > result.get("value", 0.0):
        result["value_fused_best"] = best_fps
        result["vs_baseline_fused_best"] = round(best_fps / 62_500.0, 3)
        result["fused_steps_per_dispatch"] = int(best_k[1:])
        fused_mfu = fused.get(f"{best_k}_mfu_estimate")
        if fused_mfu is not None:
            result["mfu_estimate_fused_best"] = fused_mfu


class _LearnerFixture:
    """One AOT-compiled synthetic-data learner step: shared scaffolding for
    every learner-throughput section (primary Pong, deep flagship, batch
    scaling). Data is device-resident; host publication is excluded via a
    huge publish_interval; the executable is compiled ONCE and reused for
    warmup, timing, trace capture, and cost_analysis."""

    def __init__(
        self,
        jax,
        *,
        torso,
        num_actions,
        T,
        B,
        use_lstm=False,
        fused_k=1,
        grad_accum=1,
        num_tasks=1,
        train_dtype="float32",
        health_diagnostics=False,
    ):
        import jax.numpy as jnp
        import numpy as np
        import optax

        from torched_impala_tpu.models import Agent, ImpalaNet
        from torched_impala_tpu.ops import ImpalaLossConfig, PopArtConfig
        from torched_impala_tpu.runtime import Learner, LearnerConfig

        self.jax, self.T, self.B, self.K = jax, T, B, fused_k
        self.grad_accum = grad_accum
        # num_tasks > 1 = the DMLab-30 stack: multi-task value head +
        # PopArt normalization (BASELINE config 5).
        agent = Agent(
            ImpalaNet(
                num_actions=num_actions,
                torso=torso,
                use_lstm=use_lstm,
                num_values=num_tasks,
            )
        )
        learner = Learner(
            agent=agent,
            optimizer=optax.rmsprop(6e-4, decay=0.99, eps=1e-7),
            config=LearnerConfig(
                batch_size=B,
                unroll_length=T,
                loss=ImpalaLossConfig(
                    reduction="sum",
                    health_diagnostics=health_diagnostics,
                ),
                publish_interval=1_000_000,
                steps_per_dispatch=fused_k,
                grad_accum=grad_accum,
                train_dtype=train_dtype,
                popart=(
                    PopArtConfig(num_values=num_tasks)
                    if num_tasks > 1
                    else None
                ),
            ),
            example_obs=np.zeros((84, 84, 4), np.uint8),
            rng=jax.random.key(0),
        )
        rng = np.random.default_rng(0)
        self._arrays = jax.device_put((
            jnp.asarray(
                rng.integers(0, 256, size=(T + 1, B, 84, 84, 4), dtype=np.uint8)
            ),
            jnp.asarray(rng.uniform(size=(T + 1, B)) < 0.01),
            jnp.asarray(
                rng.integers(0, num_actions, size=(T, B), dtype=np.int32)
            ),
            jnp.asarray(rng.normal(size=(T, B, num_actions)), jnp.float32),
            jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
            jnp.asarray((rng.uniform(size=(T, B)) > 0.01), jnp.float32),
            jnp.asarray(
                rng.integers(0, num_tasks, size=(B,), dtype=np.int32)
            ),
            agent.initial_state(B) if use_lstm else (),
        ))
        if fused_k > 1:
            # Superbatch with a leading K axis (same batch K times — the
            # compute is identical; only dispatch count changes).
            self._arrays = jax.device_put(
                jax.tree.map(
                    lambda x: jnp.stack([x] * fused_k), self._arrays
                )
            )
        auto_ok = False
        state = (
            learner.params,
            learner.opt_state,
            learner._popart_state,
        )
        if learner._auto_jit is not None:
            # Measure the PRODUCT path: AUTO input layouts, batch data
            # pre-laid into the step's preferred formats (what the real
            # batcher ships since LearnerConfig.auto_layouts). Probed
            # with one call: on some shapes the backend's device_put
            # returns a layout that disagrees with the compiled format
            # (observed at B=1024 on the tunnelled v5e) — fall back to
            # the plain lowering then, like the product learner does.
            # The probe call DONATES the state buffers; keep a host
            # snapshot so the fallback path can rebuild them if the
            # call fails after consuming its inputs.
            state_host = jax.tree.map(lambda x: np.asarray(x), state)
            try:
                learner._ensure_auto_compiled(self._arrays)
                from torched_impala_tpu.runtime.learner import _put_format

                auto_arrays = jax.tree.map(
                    _put_format, self._arrays, learner._batch_formats
                )
                # Re-capture AFTER ensure: it re-lays the learner's
                # state into the compiled formats; probing with the
                # stale pre-relayout references would fail the layout
                # check spuriously (review catch, r5).
                state = (
                    learner.params,
                    learner.opt_state,
                    learner._popart_state,
                )
                probe = learner._auto_compiled(*state, *auto_arrays)
                jax.block_until_ready(jax.tree.leaves(probe)[0])
                self._arrays = auto_arrays
                self._state = tuple(probe[:3])
                self.step_fn = learner._auto_compiled
                auto_ok = True
            except ValueError as e:
                # Loose 'layout' match (not the exact JAX-internal
                # wording), mirroring the product learner's fallback
                # trigger (ADVICE r5): a reworded message must still
                # fall back, not crash the bench.
                if "layout" not in str(e).lower():
                    raise
                log(
                    "bench: AUTO-layout probe disagreed at "
                    f"T={T} B={B}; using the plain step"
                )
        if not auto_ok:
            if learner._auto_jit is not None:
                # The failed probe may have consumed its donated
                # inputs; rebuild from the host snapshot.
                state = jax.device_put(state_host)
            self._state = state
            self.step_fn = learner._train_step.lower(
                *self._state, *self._arrays
            ).compile()
            # AOT executables enforce their input layouts even when
            # lowered without AUTO. On some shapes the backend's
            # device_put layout of the [K, ...] superbatch disagrees
            # with the compiled default ("Argument stacked[0]" — the
            # K=8 learner_fused crash in BENCH_live) and the first
            # execution raises; re-lay the inputs into the executable's
            # own formats instead of crashing the config.
            try:
                from torched_impala_tpu.runtime.learner import (
                    _input_formats,
                    _put_format,
                )

                fmt_args, _ = _input_formats(self.step_fn)
                self._state = jax.tree.map(
                    _put_format, self._state, tuple(fmt_args[:3])
                )
                self._arrays = jax.tree.map(
                    _put_format, self._arrays, tuple(fmt_args[3:])
                )
            except Exception as e:
                log(
                    "bench: input-format relayout unavailable: "
                    f"{type(e).__name__}: {e}"
                )
        # Warmup (first real execution).
        self.logs = self.run_steps(1)

    def run_steps(self, steps: int):
        """Run `steps` chained updates; blocks, returns the final logs."""
        state, logs = self._state, None
        for _ in range(steps):
            *state, logs = self.step_fn(*state, *self._arrays)
        self.jax.block_until_ready(logs)
        self._state = tuple(state)
        return logs

    def timed_frames_per_sec(self, steps: int) -> tuple:
        """`steps` dispatches; each carries K fused SGD steps."""
        t0 = time.perf_counter()
        self.run_steps(steps)
        dt = time.perf_counter() - t0
        return self.T * self.B * self.K * steps / dt, dt

    def flops_per_step(self) -> float:
        """XLA's algebraic FLOP count for one compiled step (0 if absent).

        Raw cost_analysis: counts every `lax.scan`/`while` BODY once, not
        x trip count — so it under-counts grad-accum programs by ~accum
        and fused-K programs by ~K. Use `canonical_flops_per_step` for
        MFU math; this raw value is only right for accum == 1 programs
        (per-dispatch, not per-SGD-step, at fused K > 1).
        """
        from torched_impala_tpu.perf import extract_compiled_cost

        flops = extract_compiled_cost(self.step_fn)["flops"]
        if flops <= 0:
            log("bench: cost_analysis reported no flops")
        return flops

    def canonical_flops_per_step(self) -> float:
        """FLOPs for ONE full-batch SGD step, under ONE convention usable
        across plain/fused/accum/remat variants of the same config
        (VERDICT r4 weak #3: the accum arm reported MFU/accum).

        - grad_accum: the accum scan body (one microbatch fwd+bwd) is
          counted once by cost_analysis, so multiply by accum. The
          optimizer-update flops get overcounted (accum-1) extra times,
          a <1% error at these model sizes (pinned ~10% by
          tests/test_bench_units.py).
        - fused K: the K-step body is likewise counted once, and one
          body IS one full SGD step — no correction; callers divide
          wall time by K dispatch-steps instead.
        - remat: recompute flops are real executed work but NOT model
          flops; MFU convention divides MODEL flops by time, so remat
          arms should prefer the plain arm's count when available.
        """
        return self.flops_per_step() * self.grad_accum

    def temp_bytes(self) -> int:
        """Compiled executable's temp (activation) HBM allocation; 0 if
        the backend doesn't expose memory_analysis."""
        try:
            return int(self.step_fn.memory_analysis().temp_size_in_bytes)
        except Exception as e:
            log(
                f"bench: memory_analysis unavailable: "
                f"{type(e).__name__}: {e}"
            )
            return 0


def run_bench(jax, tpu_ok: bool) -> dict:
    import jax.numpy as jnp

    from torched_impala_tpu.models import AtariShallowTorso

    # Large-batch default operating point on TPU (ISSUE 16): B=1024 is
    # the headline row — the MXU runs closest to peak there and the
    # linear lr-scaling + warmup schedule (configs.make_lr_schedule)
    # keeps training equivalent. A reduced batch on the CPU fallback so
    # the run finishes in minutes (labeled non-comparable anyway).
    T, B = (20, 1024) if tpu_ok else (20, 32)
    log(f"bench: backend={jax.default_backend()} T={T} B={B}")
    # bf16 torso matches the pong preset (configs.py): conv FLOPs on the
    # MXU fast path, heads/loss in f32.
    fx = _LearnerFixture(
        jax,
        torso=AtariShallowTorso(dtype=jnp.bfloat16),
        num_actions=6,  # Pong
        T=T,
        B=B,
    )
    log(f"bench: compiled, total_loss={float(fx.logs['total_loss']):.3f}")

    steps = 30 if tpu_ok else 5
    # Steady-state warmup window before the timed one (r4: the first
    # post-compile window reads ~10% slow through the tunnel; see
    # run_bench_anakin for the opposite under-block artifact).
    if tpu_ok:
        fx.run_steps(8)
    frames_per_sec, dt = fx.timed_frames_per_sec(steps)

    trace_dir = None
    if tpu_ok:
        # SURVEY.md §6 tracing row: capture a real profiler trace of a few
        # steady-state steps (outside the timed window) for MFU/infeed
        # analysis; committed under traces/ for the round notes.
        try:
            trace_dir = os.path.join(REPO, "traces", "bench")
            with jax.profiler.trace(trace_dir, create_perfetto_link=False):
                fx.run_steps(5)
            log(f"bench: profiler trace captured in {trace_dir}")
        except Exception as e:
            log(f"bench: trace capture failed: {type(e).__name__}: {e}")
            trace_dir = None

    n_chips = max(1, len(jax.devices()))
    value = frames_per_sec / n_chips
    result = {
        "metric": "learner_frames_per_sec_per_chip_pong",
        "value": round(value, 1),
        "unit": "frames/s/chip",
        "vs_baseline": round(value / 62_500.0, 3),
        "backend": jax.default_backend(),
        # Host parallelism context: this build box exposes ONE CPU core, so
        # actor-side (thread/process) throughput here is a lower bound —
        # production hosts with real core counts scale the env fleet.
        "host_cpus": os.cpu_count(),
    }
    if trace_dir is not None:
        result["profile_trace_dir"] = trace_dir
    # Rough MFU vs the v5e bf16 peak (197 TFLOP/s/chip): XLA counts
    # algebraic flops, not MXU-padded ones.
    flops = fx.canonical_flops_per_step()
    if flops > 0:
        result["train_step_gflops"] = round(flops / 1e9, 2)
        if tpu_ok:
            result["mfu_estimate"] = round((flops * steps / dt) / 197e12, 4)
    if not tpu_ok:
        result["note"] = (
            "TPU tunnel unreachable at bench time (wedged machine-wide "
            "for the whole of round 3 — tunnel_watch.log records 10+ "
            "hours of failed bounded probes); CPU fallback number — not "
            "comparable to the 62.5k/chip TPU yardstick. Latest real-chip "
            "evidence is committed in docs/evidence/BENCH_live.json (502k learner "
            "frames/s/chip, vs_baseline 8.04, captured 2026-07-29) with "
            "the profiler trace under traces/bench/; tunnel_watch.sh + "
            "tools/tunnel_watch_respawn.sh auto-capture and commit a "
            "fresh full-section run the moment the tunnel heals."
        )
    log(
        f"bench: {steps} steps in {dt:.3f}s -> {frames_per_sec:,.0f} frames/s "
        f"on {n_chips} {jax.default_backend()} device(s)"
    )
    return result


def run_bench_deep(jax) -> dict:
    """Flagship-model learner throughput: IMPALA deep ResNet + LSTM(256) at
    the breakout preset's shapes (T=20, B=32, bf16 torso — BASELINE config 3).
    Secondary to the headline Pong number; measures the model family the
    Breakout/DMLab presets actually train. TPU-only (skipped on the CPU
    fallback — the deep stack takes minutes to compile there)."""
    import jax.numpy as jnp

    from torched_impala_tpu.models import AtariDeepTorso

    T, B, steps = 20, 32, 30
    fx = _LearnerFixture(
        jax,
        torso=AtariDeepTorso(dtype=jnp.bfloat16),
        num_actions=4,
        T=T,
        B=B,
        use_lstm=True,
    )
    # Steady-state warmup window: the first post-compile window reads
    # ~10% SLOW for the learner fixtures (see run_bench; the anakin
    # runners have the opposite, under-blocking artifact).
    fx.run_steps(8)
    fps, dt = fx.timed_frames_per_sec(steps)
    out = {
        "frames_per_sec_per_chip": round(fps, 1),
        "model": "deep_resnet+lstm256",
        "T": T,
        "B": B,
    }
    def variant(key, label, **fixture_kwargs):
        """One deep-stack variant: build, warm a steady-state window,
        time, record under `key` ({"error": ...} on per-variant failure)."""
        try:
            vfx = _LearnerFixture(
                jax,
                torso=AtariDeepTorso(dtype=jnp.bfloat16),
                T=T,
                use_lstm=True,
                **fixture_kwargs,
            )
            vfx.run_steps(6)
            vfps, _ = vfx.timed_frames_per_sec(steps)
            out[key] = round(vfps, 1)
            log(f"bench: deep {label}: {vfps:,.0f} f/s")
        except Exception as e:
            out[key] = {"error": f"{type(e).__name__}: {e}"[:160]}

    # The DMLab-30 MODEL stack — deep ResNet + LSTM + 30-task PopArt
    # head + grad-accum 4 (the PopArt x accum composition landed r4 via
    # batch-end statistics) — at THIS HARNESS's shapes (84x84x4 uint8,
    # T=20), NOT the dmlab30 preset's own step (72x96x3, T=100, no
    # accum): it isolates the cost of the PopArt/multi-task machinery on
    # the same workload every other deep number here uses.
    variant(
        "deep_popart30_accum4",
        "popart30+accum4 (harness shapes)",
        num_actions=15,
        B=B,
        num_tasks=30,
        grad_accum=4,
    )
    # Batch headroom past the preset's B=32: the deep stack keeps scaling
    # (r4 measured 70k/78k/84k at B=32/64/128, temp 0.6/1.3/2.4 GB).
    variant(
        "frames_per_sec_per_chip_B128", "B=128", num_actions=4, B=128
    )
    flops = fx.canonical_flops_per_step()
    if flops > 0:
        out["train_step_gflops"] = round(flops / 1e9, 2)
        out["mfu_estimate"] = round((flops * steps / dt) / 197e12, 4)
    # bf16-coverage audit (VERDICT r2 item 3): the LSTM core runs f32 by
    # design (recurrent numerics); quantify its algebraic-FLOP share by
    # differencing against the same model without the recurrent core — the
    # f32 share bounds how much MFU the bf16 MXU path can ever reach.
    if flops > 0:
        fx_nolstm = _LearnerFixture(
            jax,
            torso=AtariDeepTorso(dtype=jnp.bfloat16),
            num_actions=4,
            T=T,
            B=B,
            use_lstm=False,
        )
        flops_nolstm = fx_nolstm.flops_per_step()
        if flops_nolstm > 0:
            out["lstm_f32_flops_share"] = round(
                max(0.0, flops - flops_nolstm) / flops, 4
            )
            fps2, dt2 = fx_nolstm.timed_frames_per_sec(steps)
            out["no_lstm_frames_per_sec"] = round(fps2, 1)
            out["no_lstm_mfu_estimate"] = round(
                (flops_nolstm * steps / dt2) / 197e12, 4
            )
    log(f"bench: deep learner {steps} steps in {dt:.3f}s -> {fps:,.0f} f/s")
    return out


def run_bench_remat(jax) -> dict:
    """Activation-memory levers on the deep ResNet at a batch where
    activations dominate HBM: torso rematerialization (configs.remat_torso
    / --remat-torso) and gradient accumulation (LearnerConfig.grad_accum /
    --grad-accum), alone and combined — throughput cost vs temp-HBM saving
    of each. The interesting read: how much bigger each lever lets B grow
    before HBM bounds it (MFU campaign; SURVEY.md §7)."""
    import flax.linen as nn
    import jax.numpy as jnp

    from torched_impala_tpu.models import AtariDeepTorso

    out = {}
    T, B, steps = 20, 64, 15
    plain = AtariDeepTorso(dtype=jnp.bfloat16)
    remat = nn.remat(AtariDeepTorso)(dtype=jnp.bfloat16)
    # ONE FLOPs model for every arm (VERDICT r4 weak #3: per-arm raw
    # cost_analysis gave accum4 MFU/4 and would credit remat's recompute
    # as model flops): the plain arm's count is canonical; arms that run
    # before/without it fall back to their own accum-corrected count.
    canonical_flops = 0.0
    for key, torso, accum in (
        ("plain", plain, 1),
        ("remat", remat, 1),
        ("accum4", plain, 4),
        ("remat_accum4", remat, 4),
    ):
        # Per-arm failure isolation: if the PLAIN arm OOMs (the exact
        # HBM-bound regime remat targets), the remat arm must still be
        # measured — that is the section's point.
        try:
            fx = _LearnerFixture(
                jax, torso=torso, num_actions=4, T=T, B=B, use_lstm=True,
                grad_accum=accum,
            )
            fx.run_steps(6)  # steady-state warmup window (r4 protocol)
            fps, dt = fx.timed_frames_per_sec(steps)
            entry = {"frames_per_sec": round(fps, 1)}
            if key == "plain":
                canonical_flops = fx.canonical_flops_per_step()
            flops = canonical_flops or fx.canonical_flops_per_step()
            if flops > 0:
                entry["mfu_estimate"] = round(
                    (flops * steps / dt) / 197e12, 4
                )
                entry["mfu_flops_source"] = (
                    "plain" if canonical_flops else "self_accum_corrected"
                )
            tb = fx.temp_bytes()
            if tb:
                entry["temp_MB"] = round(tb / 1e6, 1)
        except Exception as e:
            entry = {"error": f"{type(e).__name__}: {e}"[:200]}
        out[key] = entry
        log(f"bench: remat {key} T={T} B={B}: {entry}")
    if out.get("plain", {}).get("temp_MB") and out.get("remat", {}).get(
        "temp_MB"
    ):
        out["temp_saving_frac"] = round(
            1.0 - out["remat"]["temp_MB"] / out["plain"]["temp_MB"], 4
        )
    return out


def run_bench_fused(
    jax,
    ks=(4, 8),
    single_step_flops: float = 0.0,
    include_b64: bool = True,
) -> dict:
    """Fused-dispatch learner throughput (LearnerConfig.steps_per_dispatch):
    K SGD steps per dispatched XLA program. At the B=256 headline shapes
    the ~10 ms step already hides dispatch latency and fusing COSTS ~12%;
    at B=64 the ~2.5 ms step sits below the tunnel's ~6.6 ms per-dispatch
    latency floor and K=8 recovers +58% (190k -> 300k, r4) — the B64_K8
    config pins the regime where the feature wins (docs/SCALING.md states
    the decision rule). `include_b64=False` keeps the --fast capture at
    one compile. TPU-only."""
    import jax.numpy as jnp

    from torched_impala_tpu.models import AtariShallowTorso

    # Same per-chip normalization as the primary metric (run_bench) so the
    # value_fused_best side keys in main() compare like units with `value`.
    n_chips = max(1, len(jax.devices()))
    out = {}
    # (key, B, K, warmup, timed dispatches); MFU is only meaningful for
    # the B=256 configs that share the headline's per-step flop count.
    configs = [(f"K{K}", 256, K, 3, max(1, 30 // K)) for K in ks]
    if include_b64:
        configs.append(("B64_K8", 64, 8, 8, 4))

    def _one(B, K, warmup, dispatches):
        fx = _LearnerFixture(
            jax,
            torso=AtariShallowTorso(dtype=jnp.bfloat16),
            num_actions=6,
            T=20,
            B=B,
            fused_k=K,
        )
        # Steady-state warmup WINDOW before the timed one (r4
        # protocol: see run_bench).
        fx.run_steps(warmup)
        fps, dt = fx.timed_frames_per_sec(dispatches)
        return fx, fps, dt

    for key, B, K, warmup, dispatches in configs:
        try:
            try:
                fx, fps, dt = _one(B, K, warmup, dispatches)
            except ValueError as e:
                # jit-boundary layout refusal at high K (the K8 crash
                # the fixture relayout should prevent): fall back to
                # K=4 at the same total step count, like the product
                # learner's perf/fused_fallbacks path, instead of
                # losing the config.
                if "layout" not in str(e).lower() or K <= 4:
                    raise
                log(
                    f"bench: fused {key}: layout mismatch at K={K}; "
                    "falling back to K=4"
                )
                dispatches = max(1, dispatches * K // 4)
                K = 4
                fx, fps, dt = _one(B, K, warmup, dispatches)
                out[f"{key}_fallback_k"] = K
            out[key] = round(fps / n_chips, 1)
            if B == 256:
                # XLA's cost_analysis counts a scan/while BODY once, not
                # x trip count (measured r4: the fused-K=8 executable
                # reports ~1x the single-step flops, which made the old
                # per-dispatch formula report MFU/K). The headline
                # section's cost_analysis of the IDENTICAL model/shapes
                # at K=1 is the reliable per-step count, so prefer it.
                flops = fx.flops_per_step()
                per_step = (
                    single_step_flops if single_step_flops > 0 else flops
                )
                if per_step > 0:
                    out[f"{key}_mfu_estimate"] = round(
                        (per_step * K * dispatches / dt) / 197e12, 4
                    )
                if flops > 0:
                    out[f"{key}_costanalysis_gflops"] = round(
                        flops / 1e9, 1
                    )
            log(f"bench: fused {key}: {out[key]:,.0f} frames/s/chip")
        except TimeoutError:
            raise  # the one-shot wall-clock alarm must reach section()
        except Exception as e:
            out[key] = {"error": f"{type(e).__name__}: {e}"[:160]}
    return out


def run_bench_scaling(jax) -> dict:
    """Learner frames/s/chip AND MFU vs batch size at the Pong config
    (T=20, bf16 Nature-CNN): shows how far the single-chip number scales
    past the B=256 headline before HBM/MXU saturate, and whether MFU keeps
    climbing with batch (VERDICT r2 item 3's MFU-vs-batch curve).
    TPU-only."""
    import jax.numpy as jnp

    from torched_impala_tpu.models import AtariShallowTorso

    out = {}
    for B in (64, 256, 1024):
        fx = _LearnerFixture(
            jax,
            torso=AtariShallowTorso(dtype=jnp.bfloat16),
            num_actions=6,
            T=20,
            B=B,
        )
        fx.run_steps(6)  # steady-state warmup window (r4 protocol)
        fps, dt = fx.timed_frames_per_sec(15)
        out[f"B{B}"] = round(fps, 1)
        flops = fx.flops_per_step()
        if flops > 0:
            out[f"B{B}_mfu_estimate"] = round(
                (flops * 15 / dt) / 197e12, 4
            )
        log(f"bench: scaling B={B}: {out[f'B{B}']:,.0f} frames/s "
            f"mfu={out.get(f'B{B}_mfu_estimate')}")
    return out


def run_bench_compute(jax, tiny: bool = False, headline_mfu=None) -> dict:
    """Compute-side MFU section (ISSUE 16): same-backend step-time
    ratios for the two new compute paths, plus the B=1024 headline MFU.

    - train_dtype_step_ratio: full-bf16 train step / f32 train step
      (LearnerConfig.train_dtype; params+activations bf16 inside the
      loss, f32 optimizer/PopArt/V-trace accumulators). Budgeted < 1.0
      on TPU only — CPU bf16 is software-emulated and reads slower.
    - lstm_fused_step_ratio: fused Pallas LSTM cell unroll
      (models/lstm.py) / flax OptimizedLSTMCell unroll, fwd+bwd.
      Interpret mode off-TPU, so the tiny row only proves the path runs.
    - mfu_b1024: the headline fixture's MFU estimate at the B=1024
      default operating point (TPU runs only; passed in from the
      headline section rather than recompiling the same program).
    """
    import time as _time

    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    from torched_impala_tpu.models import AtariShallowTorso
    from torched_impala_tpu.models.lstm import PallasLSTMCell

    T, B = (5, 8) if tiny else (20, 256)
    steps = 3 if tiny else 15
    out = {}

    # -- full-bf16 step vs f32 step (identical program shape) ----------
    times = {}
    for train_dtype in ("float32", "bfloat16"):
        fx = _LearnerFixture(
            jax,
            torso=AtariShallowTorso(dtype=jnp.bfloat16),
            num_actions=6,
            T=T,
            B=B,
            train_dtype=train_dtype,
        )
        fx.run_steps(1 if tiny else 6)
        _, dt = fx.timed_frames_per_sec(steps)
        times[train_dtype] = dt / steps
        out[f"{train_dtype}_step_ms"] = round(1e3 * dt / steps, 3)
    out["train_dtype_step_ratio"] = round(
        times["bfloat16"] / times["float32"], 4
    )

    # -- fused vs flax LSTM cell unroll (fwd+bwd through a scan) -------
    H = 32 if tiny else 256
    Tl, Bl = (4, 8) if tiny else (20, 64)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.normal(size=(Tl, Bl, H)), jnp.float32)
    carry0 = (jnp.zeros((Bl, H), jnp.float32),) * 2

    def _unroll_loss(cell_cls):
        class _Unroll(nn.Module):
            @nn.compact
            def __call__(self, xs):
                scan = nn.scan(
                    lambda cell, carry, x: cell(carry, x),
                    variable_broadcast="params",
                    split_rngs={"params": False},
                    in_axes=0,
                    out_axes=0,
                )
                _, ys = scan(cell_cls(H, name="lstm"), carry0, xs)
                return jnp.sum(ys)

        mod = _Unroll()
        params = mod.init(jax.random.key(0), xs)
        step = jax.jit(jax.value_and_grad(lambda p: mod.apply(p, xs)))
        jax.block_until_ready(step(params))  # compile + warmup
        t0 = _time.perf_counter()
        for _ in range(steps):
            loss, grads = step(params)
        jax.block_until_ready(grads)
        return (_time.perf_counter() - t0) / steps

    flax_t = _unroll_loss(nn.OptimizedLSTMCell)
    fused_t = _unroll_loss(PallasLSTMCell)
    out["lstm_flax_unroll_ms"] = round(1e3 * flax_t, 3)
    out["lstm_fused_unroll_ms"] = round(1e3 * fused_t, 3)
    out["lstm_fused_step_ratio"] = round(fused_t / flax_t, 4)

    if headline_mfu is not None:
        out["mfu_b1024"] = headline_mfu

    backend = jax.default_backend()
    _history_append(
        "compute",
        {
            k: out[k]
            for k in ("train_dtype_step_ratio", "lstm_fused_step_ratio")
        },
        tiny=tiny,
        direction="lower",
        backend=backend,
    )
    if headline_mfu is not None:
        _history_append(
            "compute",
            {"mfu_b1024": headline_mfu},
            tiny=tiny,
            direction="higher",
            backend=backend,
        )
    log(
        f"bench: compute train_dtype_ratio="
        f"{out['train_dtype_step_ratio']} lstm_fused_ratio="
        f"{out['lstm_fused_step_ratio']} mfu_b1024={headline_mfu}"
    )
    return out


def run_bench_anakin(jax, tpu_ok: bool) -> dict:
    """Fully on-device actor-learner throughput (runtime/anakin.py): pure-JAX
    CartPole envs + MLP policy + V-trace update fused into one XLA program.
    This is the TPU-native architecture the 1M env-frames/s north star
    (BASELINE.json:5) actually favours — no host actors, no H2D, the env IS
    part of the compiled step. env-frames/s = E * T * iters / wall."""
    import optax

    from torched_impala_tpu.envs import JaxCartPole
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.ops import ImpalaLossConfig
    from torched_impala_tpu.runtime import AnakinConfig, AnakinRunner

    E, T, iters = (2048, 32, 30) if tpu_ok else (64, 16, 5)
    result = {"E": E, "T": T}
    # N=1 baseline and fused-dispatch variant (updates_per_dispatch=8:
    # scan 8 rollout+update iterations per dispatched program).
    for N in (1, 8) if tpu_ok else (1,):
        runner = AnakinRunner(
            agent=Agent(
                ImpalaNet(
                    num_actions=2, torso=MLPTorso(hidden_sizes=(64, 64))
                )
            ),
            env=JaxCartPole(),
            optimizer=optax.rmsprop(3e-4, decay=0.99, eps=1e-7),
            config=AnakinConfig(
                num_envs=E,
                unroll_length=T,
                loss=ImpalaLossConfig(reduction="mean"),
                updates_per_dispatch=N,
            ),
            rng=jax.random.key(0),
        )
        # Warmup WINDOW (compiles on its first dispatch), then the timed
        # window: through the tunnel the first run() window after compile
        # under-blocks (measured r4: bogus 300M+ f/s first windows;
        # windows 1+ agree to ~3%). A quarter-size warmup suffices —
        # run() ends in block_until_ready, so steady state is reached
        # before the timed window regardless of warmup length.
        runner.run(max(1, iters // N // 4))
        out = runner.run(max(1, iters // N))
        key = "env_frames_per_sec" if N == 1 else f"env_frames_per_sec_N{N}"
        result[key] = round(out["frames_per_sec"], 1)
        log(
            f"bench: anakin E={E} T={T} N={N}: "
            f"{out['frames_per_sec']:,.0f} env-frames/s on-device"
        )
    best = max(
        v for k, v in result.items() if k.startswith("env_frames_per_sec")
    )
    result["vs_north_star_1M"] = round(best / 1_000_000.0, 3)
    return result


# Locked most-promising (E, T, N) configs for the fast capture mode.
# Re-tuned from the r4 steady-state full-sweep re-run (docs/evidence/BENCH_live.json
# anakin_pixels, warmup-window protocol): N=1 beat N=8 at every (E, T)
# on the current low-dispatch-latency tunnel, and with first-window
# noise removed the program is compute-bound by E=128 — E128_T20 led at
# 440k with E128_T40 next (426k); larger E buys nothing.
# Locked fast-mode configs, retuned each time the step changes: the r5
# bootstrap-concat removal shortened the update enough that N=8
# dispatch fusion pays again (final r5 capture best: E128_T10_N8).
ANAKIN_PIXELS_LOCKED = ((128, 20, 1), (128, 10, 8))


def run_bench_anakin_pixels(jax, fast: bool = False) -> dict:
    """On-device throughput at Atari pixel shapes: JaxPixelSignal 84x84x4 +
    bf16 Nature-CNN, rollout+train fused (runtime/anakin.py). The closest
    apples-to-apples on-device comparison to the host-actor Pong pipeline:
    same obs shape, same torso, same loss — but env stepping is on-chip.

    This is the framework's best shot at the >=62.5k env-frames/s/chip
    north star INCLUDING env stepping (VERDICT r2 item 2), so it sweeps
    num_envs x updates_per_dispatch (then unroll length at the winner),
    reports the per-config table, and captures a profiler trace of the
    best configuration under traces/anakin_pixels/."""
    import jax.numpy as jnp
    import optax

    from torched_impala_tpu.envs import JaxPixelSignal
    from torched_impala_tpu.models import Agent, AtariShallowTorso, ImpalaNet
    from torched_impala_tpu.ops import ImpalaLossConfig
    from torched_impala_tpu.runtime import AnakinConfig, AnakinRunner

    def measure(E: int, T: int, N: int, frames_target: int = 300_000):
        runner = AnakinRunner(
            agent=Agent(
                ImpalaNet(
                    num_actions=4,
                    torso=AtariShallowTorso(dtype=jnp.bfloat16),
                )
            ),
            env=JaxPixelSignal(),  # 84x84x4
            optimizer=optax.rmsprop(1e-3, decay=0.99, eps=1e-7),
            config=AnakinConfig(
                num_envs=E,
                unroll_length=T,
                loss=ImpalaLossConfig(reduction="mean"),
                updates_per_dispatch=N,
            ),
            rng=jax.random.key(0),
        )
        dispatches = max(2, frames_target // (E * T * N))
        # Warmup WINDOW (quarter-size; compiles on its first dispatch)
        # then timed window: the first post-compile run() under-blocks
        # through the tunnel (see run_bench_anakin).
        runner.run(max(1, dispatches // 4))
        out = runner.run(dispatches)
        return runner, round(out["frames_per_sec"], 1)

    result = {"obs": "84x84x4 uint8", "model": "nature_cnn_bf16",
              "sweep": {}}
    best = (None, 0.0, None)  # (key, fps, (E, T, N))
    if fast:
        # Locked configs only — one compile each, no exploration. Banked
        # fast beats swept thoroughly when the tunnel-heal window is short.
        for E, T, N in ANAKIN_PIXELS_LOCKED:
            key = f"E{E}_T{T}_N{N}"
            _, fps = measure(E, T, N, frames_target=200_000)
            result["sweep"][key] = fps
            log(f"bench: anakin pixels {key}: {fps:,.0f} env-frames/s")
            if fps > best[1]:
                best = (key, fps, (E, T, N))
    else:
        for E in (128, 256, 512):
            for N in (1, 8):
                key = f"E{E}_T20_N{N}"
                _, fps = measure(E, 20, N)
                result["sweep"][key] = fps
                log(f"bench: anakin pixels {key}: {fps:,.0f} env-frames/s")
                if fps > best[1]:
                    best = (key, fps, (E, 20, N))
        # Unroll length at the winning (E, N): T trades per-dispatch compute
        # against update frequency but not frame math (E*T*N per dispatch).
        E, _, N = best[2]
        for T in (10, 40, 64):
            key = f"E{E}_T{T}_N{N}"
            _, fps = measure(E, T, N)
            result["sweep"][key] = fps
            log(f"bench: anakin pixels {key}: {fps:,.0f} env-frames/s")
            if fps > best[1]:
                best = (key, fps, (E, T, N))
    result["env_frames_per_sec"] = best[1]
    result["best_config"] = best[0]
    result["vs_north_star_62500_per_chip"] = round(best[1] / 62_500.0, 3)
    if fast:
        return result  # no trace capture: every second counts in fast mode
    # The FLAGSHIP model on-device: deep IMPALA ResNet at pixel shapes
    # with env stepping fused in (r4 tuning measured 73k env-f/s = 1.17x
    # the per-chip north-star share — the deep model clears the bar
    # without any host feeding at all).
    try:
        from torched_impala_tpu.models import AtariDeepTorso

        deep_E, deep_T = 256, 20
        deep_runner = AnakinRunner(
            agent=Agent(
                ImpalaNet(
                    num_actions=4, torso=AtariDeepTorso(dtype=jnp.bfloat16)
                )
            ),
            env=JaxPixelSignal(),
            optimizer=optax.rmsprop(1e-3, decay=0.99, eps=1e-7),
            config=AnakinConfig(
                num_envs=deep_E,
                unroll_length=deep_T,
                loss=ImpalaLossConfig(reduction="mean"),
                updates_per_dispatch=1,
            ),
            rng=jax.random.key(0),
        )
        deep_runner.run(10)
        deep = deep_runner.run(40)
        result["deep_resnet"] = {
            "E": deep_E,
            "T": deep_T,
            "env_frames_per_sec": round(deep["frames_per_sec"], 1),
            "vs_north_star_62500_per_chip": round(
                deep["frames_per_sec"] / 62_500.0, 3
            ),
        }
        log(
            f"bench: anakin pixels deep_resnet E{deep_E} T{deep_T}: "
            f"{deep['frames_per_sec']:,.0f} env-frames/s"
        )
    except Exception as e:
        result["deep_resnet"] = {"error": f"{type(e).__name__}: {e}"[:200]}
        log(f"bench: anakin deep failed: {type(e).__name__}: {e}")
    # Trace the winner for the round notes (SURVEY.md §6 tracing row).
    try:
        E, T, N = best[2]
        runner, _ = measure(E, T, N, frames_target=0)
        trace_dir = os.path.join(REPO, "traces", "anakin_pixels")
        with jax.profiler.trace(trace_dir, create_perfetto_link=False):
            runner.run(2)
        result["profile_trace_dir"] = trace_dir
    except Exception as e:
        log(f"bench: anakin pixels trace failed: {type(e).__name__}: {e}")
    return result


def run_feeder_saturation(jax, tpu_ok: bool) -> dict:
    """Host-feed ceiling WITHOUT env stepping (VERDICT r2 item 4, r3
    item 3, r4 weak #1): feeder threads replay precomputed per-unroll
    Trajectories at maximum rate through the REAL Learner ingest path —
    host queue -> batcher thread stacking B unrolls -> device_put ->
    bounded device queue. Modes per (B, K) config:

    - drain_cpu: batches pulled straight off the device queue with NO
      train step, device_put targeted at the LOCAL CPU backend
      (LearnerConfig.data_device) — the host-work ceiling of the path.
      Caveat it self-reports: jax CPU device_put may zero-copy ALIAS,
      so the ring-reuse stacking auto-disables here; the reuse win is
      measured separately (stack_reuse_compare, incl. a simulated-H2D
      arm), and `host_path_ceiling` below combines the two into the
      per-core product answer.
    - drain (TPU backends): same path to the default device. On THIS
      rig that crosses a network tunnel, so it measures the tunnel, not
      host work or production PCIe H2D — the r4 capture recorded 826
      f/s here without saying so and contradicted the notes' CPU-run
      table by ~100x. Every entry now records `device_put_target` and
      `route`.
    - train: feed + real train step + batch_wait_frac, TPU only (probes
      whether compute or feed binds first ON THIS RIG; through the
      tunnel the answer reflects tunnel latency too).

    THE number the host-actor architecture stands on: at ~29.7 KB/frame,
    the 62.5k frames/s/chip north-star pace needs ~1.9 GB/s of sustained
    ingest per chip (see required_* keys)."""
    import threading

    import jax.numpy as jnp
    import numpy as np
    import optax

    from torched_impala_tpu.models import Agent, AtariShallowTorso, ImpalaNet
    from torched_impala_tpu.ops import ImpalaLossConfig
    from torched_impala_tpu.runtime import Learner, LearnerConfig
    from torched_impala_tpu.runtime.learner import QueueClosed
    from torched_impala_tpu.runtime.types import Trajectory

    T, A = 20, 6
    rng = np.random.default_rng(0)

    def make_traj(i: int) -> Trajectory:
        return Trajectory(
            obs=rng.integers(0, 256, size=(T + 1, 84, 84, 4), dtype=np.uint8),
            first=np.zeros((T + 1,), np.bool_),
            actions=rng.integers(0, A, size=(T,)).astype(np.int32),
            behaviour_logits=rng.normal(size=(T, A)).astype(np.float32),
            rewards=rng.normal(size=(T,)).astype(np.float32),
            cont=np.ones((T,), np.float32),
            agent_state=(),
            actor_id=i,
            param_version=0,
            task=0,
        )

    pool = [make_traj(i) for i in range(64)]
    unroll_bytes = sum(
        x.nbytes
        for x in (
            pool[0].obs,
            pool[0].first,
            pool[0].actions,
            pool[0].behaviour_logits,
            pool[0].rewards,
            pool[0].cont,
        )
    )

    def measure(
        B: int,
        K: int,
        steps: int,
        drain_only: bool = False,
        data_device: str | None = None,
    ) -> dict:
        learner = Learner(
            agent=Agent(
                ImpalaNet(
                    num_actions=A,
                    torso=AtariShallowTorso(
                        dtype=jnp.bfloat16 if tpu_ok else jnp.float32
                    ),
                )
            ),
            optimizer=optax.rmsprop(6e-4, decay=0.99, eps=1e-7),
            config=LearnerConfig(
                batch_size=B,
                unroll_length=T,
                loss=ImpalaLossConfig(reduction="sum"),
                publish_interval=1_000_000,
                steps_per_dispatch=K,
                data_device=data_device,
            ),
            example_obs=np.zeros((84, 84, 4), np.uint8),
            rng=jax.random.key(0),
        )
        learner.start()
        stop = threading.Event()

        def feeder(offset: int) -> None:
            i = offset
            while not stop.is_set():
                try:
                    learner.enqueue(pool[i % len(pool)])
                except QueueClosed:
                    return
                i += 1

        feeders = [
            threading.Thread(target=feeder, args=(j * 17,), daemon=True)
            for j in range(2)
        ]
        for th in feeders:
            th.start()
        try:
            if drain_only:
                # Pull assembled device batches off the bounded queue with
                # no train step: host queue -> stacking -> device_put is
                # the whole measured path.
                arrays, _, _ = learner._batch_q.get(timeout=600)  # warmup
                t0 = time.perf_counter()
                for _ in range(steps):
                    arrays, _, _ = learner._batch_q.get(timeout=600)
                jax.block_until_ready(jax.tree.leaves(arrays)[0])
                dt = time.perf_counter() - t0
                wait_frac = None
            else:
                learner.step_once(timeout=600)  # compile + first batch
                wait0 = learner._wait_accum
                t0 = time.perf_counter()
                for _ in range(steps):
                    learner.step_once(timeout=600)
                jax.block_until_ready(
                    jax.tree.leaves(learner.params)[0]
                )
                dt = time.perf_counter() - t0
                wait_frac = (learner._wait_accum - wait0) / dt
        finally:
            stop.set()
            learner.stop()
            for th in feeders:
                th.join(timeout=10)
        frames = T * B * K * steps
        # Self-description (VERDICT r4 weak #1): WHERE did device_put
        # land, and did the transfer cross this rig's network tunnel?
        target = (
            jax.local_devices(backend=data_device)[0]
            if data_device
            else jax.devices()[0]
        )
        entry = {
            "frames_per_sec": round(frames / dt, 1),
            "ingest_MB_per_sec": round(
                unroll_bytes * B * K * steps / dt / 1e6, 1
            ),
            "steps": steps,
            # Whether the ring-reuse stacking path engaged (auto-resolved
            # by the aliasing probe; the big lever at large B).
            "stack_reuse": bool(learner._stack_reuse),
            "device_put_target": str(target),
            # Route derived from the resolved device itself (env-var
            # sniffing would mislabel tunnel transfers reached via the
            # JAX_PLATFORMS=<unset> probe rung): this rig's tunnelled
            # chip identifies as the 'axon' PJRT plugin.
            "route": (
                "local_host_memory"
                if target.platform == "cpu"
                else (
                    "tunnelled_tpu_NOT_representative_of_PCIe_H2D"
                    if "axon"
                    in getattr(target.client, "platform_version", "")
                    else "device_default"
                )
            ),
        }
        if wait_frac is not None:
            # Fraction of learner wall-time spent waiting on the batcher:
            # ~0 => device-bound even at max feed; ~1 => host-feed-bound.
            entry["batch_wait_frac"] = round(wait_frac, 4)
        else:
            entry["vs_62500_per_chip"] = round(frames / dt / 62_500.0, 3)
        return entry

    bytes_per_frame = unroll_bytes / T
    out = {
        "unroll_KB": round(unroll_bytes / 1e3, 1),
        "bytes_per_frame": round(bytes_per_frame, 1),
        # What the feed path MUST sustain: north-star pace per chip
        # (62.5k frames/s = BASELINE.json:5 / 16) and the full 16-chip
        # 1M frames/s figure, at this obs format's bytes/frame.
        "required_GBps_per_chip_62500fps": round(
            62_500 * bytes_per_frame / 1e9, 2
        ),
        "required_GBps_total_1Mfps_16chip": round(
            1_000_000 * bytes_per_frame / 1e9, 2
        ),
    }
    # CPU-backend drain sweep (host work only — the chip-independent
    # claim, now actually true): B x K grid, steps sized so each config
    # moves >=60MB of unrolls — enough to amortize warmup on this 1-core
    # box without starving the wall-clock alarm. Needs a local CPU
    # backend alongside the default one (resolve_tpu_env arranges
    # "axon,cpu"); degrades to the default backend when absent.
    try:
        jax.local_devices(backend="cpu")
        cpu_dev = "cpu"
    except Exception:
        cpu_dev = None
    if cpu_dev is None:
        # Without a local CPU backend the sweep would measure the DEFAULT
        # device — on this rig the tunnelled TPU — so a 'drain_cpu' key
        # would silently record tunnel bandwidth (ADVICE r5). Name the
        # rows for what they measure and say so explicitly.
        out["drain_note"] = (
            "no local CPU backend: drain_default_* rows measure the "
            "DEFAULT device (tunnel route on this rig), not host CPU"
        )
    drain_prefix = "drain_cpu" if cpu_dev is not None else "drain_default"
    for B in (8, 64, 256):
        for K in (1, 4):
            steps = max(3, 4096 // (B * K))
            key = f"{drain_prefix}_B{B}_K{K}"
            out[key] = measure(
                B, K, steps, drain_only=True, data_device=cpu_dev
            )
            log(f"bench: feeder {key}: {out[key]}")
    # The same drain against the DEFAULT device — on this rig that is
    # the tunnelled TPU, so this row measures the tunnel route (each
    # entry's `route` key says so); kept because batch_wait_frac in the
    # train rows below is bounded by it.
    if tpu_ok:
        for B, K in ((8, 1), (256, 1)):
            steps = max(3, 4096 // (B * K))
            key = f"drain_B{B}_K{K}"
            out[key] = measure(B, K, steps, drain_only=True)
            log(f"bench: feeder {key}: {out[key]}")
    # The per-core product answer (VERDICT r4 missing #4): the integrated
    # CPU drain above runs WITHOUT ring reuse (device_put aliasing on the
    # CPU backend disables it), so it lower-bounds the host path; the
    # ring + simulated-H2D-copy arm of stack_reuse_compare measures the
    # reuse path a production (copying-H2D) host runs. main() combines
    # both into `host_path_ceiling` next to required_GBps_per_chip.
    # Feed + train (TPU only: on CPU the train step dominates and the
    # number is uninformative — r3's B8 config measured the CPU step, not
    # the feed).
    if tpu_ok:
        for B, K, steps in ((64, 1, 12), (256, 1, 8), (256, 4, 3)):
            key = f"train_B{B}_K{K}"
            out[key] = measure(B, K, steps)
            log(f"bench: feeder {key}: {out[key]}")
    return out


def run_bench_env_pool(jax) -> dict:
    """Lockstep vs async ready-set env-pool scheduling (ISSUE 1 tentpole):
    W x E fake envs with injected per-step delays, one VectorActor doing
    batched inference over the pool. Reports env-steps/sec under 0% and
    10% straggler injection for both pool modes plus the async/lockstep
    ratio — the claim under test is that ready-set batching removes
    straggler latency from the inference critical path (>= 1.3x under
    stragglers) without giving up lockstep throughput when there are none.

    Host-side only: runs on any box (no TPU needed); inference is pinned
    to the local CPU backend when present so tunnel dispatch doesn't
    pollute the host-path numbers."""
    import numpy as np

    from torched_impala_tpu import configs
    from torched_impala_tpu.envs.fake import StragglerFactory
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime.env_pool import ProcessEnvPool
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.runtime.vector_actor import VectorActor

    # 8 workers x 4 envs: worker granularity fine enough that one
    # straggling env blocks 4 rows, not 8. Delays model an emulator
    # (~2ms/step) with long-tail stalls (50ms — a GC pause / auto-reset /
    # slow frame); at 10% injection per env step, a lockstep pool pays at
    # least one stall on ~97% of its waves (1 - 0.9^32) while an async
    # worker pays ~0.4 expected stalls per step on its own clock.
    W, E, T, unrolls = 8, 4, 20, 3
    base_delay_s, straggler_delay_s = 2e-3, 0.05
    # 0.25 measured best under stragglers on this box (waves of 2 workers:
    # 1.85x vs 1.39x at 0.5 vs 1.28x at 0.75) with no-straggler parity
    # ~0.98 at EVERY fraction — the actor's grace window coalesces full
    # batches when nobody straggles, so a small threshold costs nothing.
    ready_fraction = 0.25
    # Factory must be picklable from an importable module (forkserver):
    # the preset machinery's fake-env factory + the StragglerEnv wrapper.
    inner = configs.make_env_factory(
        configs.ExperimentConfig(
            name="bench_pool",
            env_family="cartpole",
            obs_shape=(8,),
            num_actions=4,
        ),
        fake=True,
    )
    agent = Agent(
        ImpalaNet(num_actions=4, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((8,), np.float32)
    )
    store = ParamStore()
    store.publish(0, params)
    try:
        device = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        device = None

    def measure(mode: str, prob: float) -> float:
        factory = StragglerFactory(
            inner,
            base_delay_s=base_delay_s,
            straggler_delay_s=straggler_delay_s,
            straggler_prob=prob,
        )
        pool = ProcessEnvPool(
            env_factory=factory,
            num_workers=W,
            envs_per_worker=E,
            obs_shape=(8,),
            obs_dtype=np.float32,
            mode=mode,
            ready_fraction=ready_fraction,
        )
        try:
            actor = VectorActor(
                actor_id=0,
                envs=pool,
                agent=agent,
                param_store=store,
                enqueue=lambda t: None,
                unroll_length=T,
                seed=0,
                device=device,
            )
            actor.unroll_and_push()  # warmup: compiles the wave shapes
            t0 = time.perf_counter()
            for _ in range(unrolls):
                actor.unroll_and_push()
            dt = time.perf_counter() - t0
            return unrolls * T * pool.num_envs / dt
        finally:
            pool.close()

    out = {
        "pool": f"{W}x{E} envs, T={T}, ready_fraction={ready_fraction}",
        "delays_ms": {
            "base": base_delay_s * 1e3,
            "straggler": straggler_delay_s * 1e3,
        },
    }
    for prob, tag in ((0.0, "no_stragglers"), (0.1, "stragglers_10pct")):
        lockstep = measure("lockstep", prob)
        async_sps = measure("async", prob)
        out[tag] = {
            "lockstep_env_steps_per_sec": round(lockstep, 1),
            "async_env_steps_per_sec": round(async_sps, 1),
            "async_vs_lockstep": round(async_sps / lockstep, 3),
        }
        log(f"bench: env_pool {tag}: {out[tag]}")
    return out


def run_bench_telemetry(jax) -> dict:
    """Telemetry-registry overhead (ISSUE 2 acceptance: < 2%).

    Two measurements:
    1. raw per-record cost of each metric kind (ns/op, single thread) —
       the intrinsic hot-path price;
    2. env-pool steps/s through the instrumented VectorActor+
       ProcessEnvPool pipeline with the global registry ENABLED vs
       DISABLED (`telemetry.set_enabled`) — the end-to-end overhead the
       acceptance bound is written against. Envs run with a small 1ms
       base delay (no stragglers) so per-step telemetry cost is measured
       against a realistic-but-tight step budget instead of vanishing
       under a slow emulator.

    Host-side only: no TPU needed; inference pinned to the CPU backend
    when present (same protocol as the env_pool section)."""
    import numpy as np

    from torched_impala_tpu import configs
    from torched_impala_tpu.envs.fake import StragglerFactory
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime.env_pool import ProcessEnvPool
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.runtime.vector_actor import VectorActor
    from torched_impala_tpu.telemetry import Registry, set_enabled

    # 1. raw per-op costs on a fresh registry. Metric objects resolve
    # OUTSIDE the timed loop, exactly like the real call sites do.
    reg = Registry()
    c = reg.counter("bench/counter")
    g = reg.gauge("bench/gauge")
    t = reg.timer("bench/timer")
    h = reg.histogram("bench/hist_ms")
    ops = {
        "counter_inc": lambda: c.inc(),
        "gauge_set": lambda: g.set(1.0),
        "timer_observe": lambda: t.observe(1e-3),
        "hist_observe": lambda: h.observe(3.7),
    }
    N = 200_000
    raw_ns = {}
    for name, op in ops.items():
        t0 = time.perf_counter()
        for _ in range(N):
            op()
        raw_ns[name] = round((time.perf_counter() - t0) / N * 1e9, 1)
    t0 = time.perf_counter()
    for _ in range(1000):
        reg.snapshot()
    raw_ns["snapshot_us"] = round(
        (time.perf_counter() - t0) / 1000 * 1e6, 1
    )
    log(f"bench: telemetry raw ops: {raw_ns}")

    # 2. end-to-end env-pool throughput, registry on vs off.
    W, E, T, unrolls = 4, 4, 20, 3
    inner = configs.make_env_factory(
        configs.ExperimentConfig(
            name="bench_telemetry",
            env_family="cartpole",
            obs_shape=(8,),
            num_actions=4,
        ),
        fake=True,
    )
    factory = StragglerFactory(
        inner, base_delay_s=1e-3, straggler_delay_s=0.0, straggler_prob=0.0
    )
    agent = Agent(
        ImpalaNet(num_actions=4, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((8,), np.float32)
    )
    store = ParamStore()
    store.publish(0, params)
    try:
        device = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        device = None

    def measure(enabled: bool) -> float:
        set_enabled(enabled)
        pool = ProcessEnvPool(
            env_factory=factory,
            num_workers=W,
            envs_per_worker=E,
            obs_shape=(8,),
            obs_dtype=np.float32,
            mode="async",
            ready_fraction=0.5,
        )
        try:
            actor = VectorActor(
                actor_id=0,
                envs=pool,
                agent=agent,
                param_store=store,
                enqueue=lambda t: None,
                unroll_length=T,
                seed=0,
                device=device,
            )
            actor.unroll_and_push()  # warmup: compiles wave shapes
            t0 = time.perf_counter()
            for _ in range(unrolls):
                actor.unroll_and_push()
            dt = time.perf_counter() - t0
            return unrolls * T * pool.num_envs / dt
        finally:
            pool.close()
            set_enabled(True)

    # Interleaved arms, best-of-3 each: pool spawn + OS scheduling noise
    # on this 1-core box exceeds the ~0.3% effect being measured, and max
    # (the least-interrupted run) is the standard noise filter for
    # throughput arms.
    on, off = [], []
    for _ in range(3):
        on.append(measure(True))
        off.append(measure(False))
    sps_on, sps_off = max(on), max(off)
    out = {
        "raw_ns_per_op": raw_ns,
        "pool": f"{W}x{E} envs, T={T}, async, 1ms base delay",
        "env_steps_per_sec_on": round(sps_on, 1),
        "env_steps_per_sec_off": round(sps_off, 1),
        "overhead_pct": round((1.0 - sps_on / sps_off) * 100.0, 2),
    }
    log(f"bench: telemetry overhead: {out['overhead_pct']}% "
        f"(on {out['env_steps_per_sec_on']} vs off "
        f"{out['env_steps_per_sec_off']} steps/s)")
    _history_append(
        "telemetry", {"env_steps_per_sec_on": out["env_steps_per_sec_on"]}
    )
    return out


def run_bench_export(jax, tiny: bool = False) -> dict:
    """Observability-plane exposition overhead + fan-in latency
    (ISSUE 17 acceptance: scraping the OpenMetrics endpoint costs
    <= 1% of env-pool throughput).

    Three measurements:
    1. raw exposition costs — `MetricsExporter.render()` over a
       representative aggregated snapshot, and one full HTTP scrape
       roundtrip against the live endpoint (ephemeral port, stdlib
       urllib client);
    2. fan-in latency — the shared-memory snapshot lane's
       publish->read roundtrip for a worker-sized payload (snapshot +
       heartbeats + a 256-record trace tail), i.e. how stale the
       parent's view of a worker can be beyond the publish interval;
    3. end-to-end env-pool steps/s with the exporter serving scrapes
       at 20 Hz vs no exporter at all — interleaved best-of-N arms,
       the same noise protocol as the telemetry/tracing sections. The
       workers publish through the lane in BOTH arms (fan-in is
       always on, like the recorder), so the delta prices exactly
       what `--metrics-port` adds: render + serve under scrape load.

    `tiny=True` shrinks op counts and unrolls for the CI variant in
    tests/test_bench_units.py (same code path, looser assert). The
    section driver also passes tiny=True on non-TPU hosts: the
    overhead quotient of two steps/s numbers on a 1-core CPU VM swings
    several percent run-to-run (the scraper thread shares the only
    core with 4 worker processes), so only full TPU rows meet the
    perfgate `export_overhead_frac <= 0.01` pin — CPU rows carry the
    tiny_ prefix and are budget-vacuous, like the compute section."""
    import json as _json
    import threading as _threading
    import urllib.request

    import numpy as np

    from torched_impala_tpu import configs
    from torched_impala_tpu.envs.fake import StragglerFactory
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime.env_pool import ProcessEnvPool
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.runtime.vector_actor import VectorActor
    from torched_impala_tpu.telemetry import (
        FlightRecorder,
        MetricsExporter,
        SnapshotLane,
        SnapshotWriter,
        get_aggregator,
        get_registry,
    )

    # 1. raw exposition costs over a representative payload: 64 local
    # series + 4 worker blocks of 16 series each, the shape of a small
    # async run's aggregated snapshot.
    snap = {f"telemetry/bench/series_{i:02d}": float(i) for i in range(64)}
    for w in range(4):
        for i in range(16):
            snap[f"telemetry/proc0w{w}/pool/series_{i:02d}"] = float(i)
    exporter = MetricsExporter(lambda: dict(snap), port=0).start()
    try:
        N = 200 if tiny else 2_000
        t0 = time.perf_counter()
        for _ in range(N):
            exporter.render()
        render_us = round((time.perf_counter() - t0) / N * 1e6, 1)
        url = f"http://127.0.0.1:{exporter.port}/metrics"
        scrapes = 20 if tiny else 200
        t0 = time.perf_counter()
        for _ in range(scrapes):
            with urllib.request.urlopen(url, timeout=5) as resp:
                resp.read()
        scrape_us = round((time.perf_counter() - t0) / scrapes * 1e6, 1)
    finally:
        exporter.stop()

    # 2. fan-in lane roundtrip: publish a worker-sized payload, read it
    # back. This prices the lane itself; end-to-end staleness adds the
    # worker's 0.25s publish interval on top.
    rec = FlightRecorder(capacity=512)
    t_ns = time.monotonic_ns()
    for i in range(256):
        rec.complete("pool/worker_step", t_ns + i, 1000, {"lid": "a0u0"})
    payload = {
        "label": "proc0w0",
        "snapshot": {k: v for k, v in snap.items() if "proc" not in k},
        "heartbeats": {"worker": time.monotonic()},
        "trace": rec.tail(256),
        "thread_names": {},
    }
    payload_bytes = len(_json.dumps(payload).encode())
    lane = SnapshotLane(1)
    try:
        writer = SnapshotWriter(lane.descriptor(), 0)
        try:
            M = 100 if tiny else 1_000
            t0 = time.perf_counter()
            for _ in range(M):
                writer.publish(payload)
                lane.read(0)
            fanin_us = round((time.perf_counter() - t0) / M * 1e6, 1)
        finally:
            writer.close()
    finally:
        lane.close()

    # 3. end-to-end env-pool throughput, exporter+scraper on vs off.
    W, E, T = (2, 2, 10) if tiny else (4, 4, 20)
    unrolls = 2 if tiny else 3
    reps = 2 if tiny else 3
    inner = configs.make_env_factory(
        configs.ExperimentConfig(
            name="bench_export",
            env_family="cartpole",
            obs_shape=(8,),
            num_actions=4,
        ),
        fake=True,
    )
    factory = StragglerFactory(
        inner, base_delay_s=1e-3, straggler_delay_s=0.0, straggler_prob=0.0
    )
    agent = Agent(
        ImpalaNet(num_actions=4, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((8,), np.float32)
    )
    store = ParamStore()
    store.publish(0, params)
    try:
        device = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        device = None

    def measure(export: bool) -> float:
        aggregator = get_aggregator()
        registry = get_registry()
        pool = ProcessEnvPool(
            env_factory=factory,
            num_workers=W,
            envs_per_worker=E,
            obs_shape=(8,),
            obs_dtype=np.float32,
            mode="async",
            ready_fraction=0.5,
        )
        exp = None
        stop_scraper = _threading.Event()
        scraper = None
        try:
            if export:
                exp = MetricsExporter(
                    lambda: aggregator.aggregated_snapshot(
                        registry.snapshot()
                    ),
                    port=0,
                ).start()
                surl = f"http://127.0.0.1:{exp.port}/metrics"

                def scrape_loop():
                    while not stop_scraper.wait(0.05):  # 20 Hz
                        try:
                            with urllib.request.urlopen(
                                surl, timeout=5
                            ) as resp:
                                resp.read()
                        except Exception:
                            pass

                scraper = _threading.Thread(
                    target=scrape_loop, daemon=True
                )
                scraper.start()
            actor = VectorActor(
                actor_id=0,
                envs=pool,
                agent=agent,
                param_store=store,
                enqueue=lambda t: None,
                unroll_length=T,
                seed=0,
                device=device,
            )
            actor.unroll_and_push()  # warmup: compiles wave shapes
            t0 = time.perf_counter()
            for _ in range(unrolls):
                actor.unroll_and_push()
            dt = time.perf_counter() - t0
            return unrolls * T * pool.num_envs / dt
        finally:
            stop_scraper.set()
            if scraper is not None:
                scraper.join(timeout=5)
            if exp is not None:
                exp.stop()
            pool.close()

    on, off = [], []
    for _ in range(reps):
        on.append(measure(True))
        off.append(measure(False))
    sps_on, sps_off = max(on), max(off)
    out = {
        "render_us": render_us,
        "scrape_us": scrape_us,
        "fanin_roundtrip_us": fanin_us,
        "fanin_payload_bytes": payload_bytes,
        "pool": f"{W}x{E} envs, T={T}, async, 20 Hz scrape",
        "env_steps_per_sec_on": round(sps_on, 1),
        "env_steps_per_sec_off": round(sps_off, 1),
        "export_overhead_frac": round(
            max(0.0, 1.0 - sps_on / sps_off), 4
        ),
    }
    log(
        f"bench: export overhead {out['export_overhead_frac'] * 100:.2f}% "
        f"(on {out['env_steps_per_sec_on']} vs off "
        f"{out['env_steps_per_sec_off']} steps/s), fan-in roundtrip "
        f"{fanin_us}us for {payload_bytes}B"
    )
    _history_append(
        "export",
        {
            "export_overhead_frac": out["export_overhead_frac"],
            "fanin_roundtrip_us": out["fanin_roundtrip_us"],
        },
        tiny=tiny,
        direction="lower",
    )
    return out


def run_bench_health(jax, tiny: bool = False) -> dict:
    """Learning-health diagnostics overhead (ISSUE 19 acceptance: the
    in-step training-health signals — V-trace rho/c clip fractions, the
    pre-clip IS-weight log-histogram, entropy, behaviour->learner KL,
    value explained variance, per-group grad norms and update ratios —
    ride the existing train-step dispatch and cost <= 1% of step time).

    Two `_LearnerFixture` arms over identical shapes and seeds,
    differing only in `ImpalaLossConfig.health_diagnostics`; both
    compile up front, then interleaved best-of-N timed windows (the
    export section's noise protocol). The overhead is a quotient of two
    host-timed step wall-clocks, dispatch-noise-dominated on a loaded
    CPU box, so the section driver passes tiny=True off-TPU and only
    full TPU rows meet the perfgate `health_overhead_frac <= 0.01` pin
    (CPU rows carry the tiny_ prefix and are budget-vacuous)."""
    from torched_impala_tpu.models import AtariShallowTorso

    T, B = (5, 8) if tiny else (20, 256)
    steps = 3 if tiny else 15
    reps = 2 if tiny else 3
    fixtures = {}
    for on in (False, True):
        fixtures[on] = _LearnerFixture(
            jax,
            torso=AtariShallowTorso(),
            num_actions=6,
            T=T,
            B=B,
            health_diagnostics=on,
        )
        fixtures[on].run_steps(1 if tiny else 6)
    # The diagnostics must live INSIDE the compiled step: the on arm's
    # logs carry the health_* family, the off arm's carry none (the
    # off-path program is the bit-identical baseline the parity test
    # in tests/test_health.py pins).
    health_keys = sorted(
        k for k in fixtures[True].logs if k.startswith("health_")
    )
    assert health_keys, "health arm emitted no health_* in-step logs"
    assert not any(
        k.startswith("health_") for k in fixtures[False].logs
    ), "diagnostics-off arm leaked health_* logs"

    times = {False: [], True: []}
    for _ in range(reps):
        for on in (True, False):
            _, dt = fixtures[on].timed_frames_per_sec(steps)
            times[on].append(dt / steps)
    t_on, t_off = min(times[True]), min(times[False])
    out = {
        "shape": f"T={T} B={B} atari-shallow f32",
        "health_series": len(health_keys),
        "step_ms_on": round(1e3 * t_on, 3),
        "step_ms_off": round(1e3 * t_off, 3),
        "health_overhead_frac": round(max(0.0, 1.0 - t_off / t_on), 4),
    }
    log(
        f"bench: health diagnostics overhead "
        f"{out['health_overhead_frac'] * 100:.2f}% "
        f"({out['health_series']} in-step series; on "
        f"{out['step_ms_on']}ms vs off {out['step_ms_off']}ms)"
    )
    _history_append(
        "health",
        {"health_overhead_frac": out["health_overhead_frac"]},
        tiny=tiny,
        direction="lower",
    )
    return out


def run_bench_tracing(jax, tiny: bool = False) -> dict:
    """Flight-recorder overhead (ISSUE 4 acceptance: < 1% on the async
    env-pool loop with tracing always on).

    Two measurements, mirroring the telemetry section's protocol:
    1. raw per-record cost (ns/op, single thread) of each record kind —
       instant, pre-timed complete, span context manager — plus the
       export cost per retained event;
    2. env-steps/s through the instrumented VectorActor+ProcessEnvPool
       pipeline with the global recorder ENABLED vs DISABLED
       (`set_trace_enabled`) — the end-to-end bound. The recorder is
       always on in production, so the "off" arm exists only to price
       the "on" arm.

    `tiny=True` shrinks the op counts and unroll count for the CI bound
    in tests/test_bench_units.py (same code path, looser assert)."""
    import numpy as np

    from torched_impala_tpu import configs
    from torched_impala_tpu.envs.fake import StragglerFactory
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime.env_pool import ProcessEnvPool
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.runtime.vector_actor import VectorActor
    from torched_impala_tpu.telemetry import (
        FlightRecorder,
        get_recorder,
        set_trace_enabled,
    )

    # 1. raw per-op costs on a fresh recorder (same ring capacity as the
    # global one — overwrite cost is part of the steady state).
    rec = FlightRecorder()
    lineage = {"lid": "a0u0", "worker": 3}
    N = 20_000 if tiny else 200_000
    t_ns = time.monotonic_ns()

    def timed(op) -> float:
        t0 = time.perf_counter()
        for _ in range(N):
            op()
        return round((time.perf_counter() - t0) / N * 1e9, 1)

    raw_ns = {
        "instant": timed(lambda: rec.instant("bench/evt", lineage)),
        "complete": timed(
            lambda: rec.complete("bench/span", t_ns, 1000, lineage)
        ),
        "span_ctx": timed(
            lambda: rec.span("bench/ctx", lineage).__enter__()
            .__exit__(None, None, None)
        ),
        "instant_no_lineage": timed(lambda: rec.instant("bench/bare")),
    }
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "bench_trace.json")
        t0 = time.perf_counter()
        n_events = rec.export(path)
        raw_ns["export_us_per_event"] = round(
            (time.perf_counter() - t0) / max(1, n_events) * 1e6, 2
        )
        raw_ns["export_events"] = n_events
    log(f"bench: tracing raw ops: {raw_ns}")

    # 2. end-to-end env-pool throughput, recorder on vs off (identical
    # harness to the telemetry section: 1ms base delay, no stragglers).
    W, E, T = 4, 4, 20
    unrolls = 2 if tiny else 3
    inner = configs.make_env_factory(
        configs.ExperimentConfig(
            name="bench_tracing",
            env_family="cartpole",
            obs_shape=(8,),
            num_actions=4,
        ),
        fake=True,
    )
    factory = StragglerFactory(
        inner, base_delay_s=1e-3, straggler_delay_s=0.0, straggler_prob=0.0
    )
    agent = Agent(
        ImpalaNet(num_actions=4, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((8,), np.float32)
    )
    store = ParamStore()
    store.publish(0, params)
    try:
        device = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        device = None

    def measure(enabled: bool) -> float:
        set_trace_enabled(enabled)
        pool = ProcessEnvPool(
            env_factory=factory,
            num_workers=W,
            envs_per_worker=E,
            obs_shape=(8,),
            obs_dtype=np.float32,
            mode="async",
            ready_fraction=0.5,
        )
        try:
            actor = VectorActor(
                actor_id=0,
                envs=pool,
                agent=agent,
                param_store=store,
                enqueue=lambda t: None,
                unroll_length=T,
                seed=0,
                device=device,
            )
            actor.unroll_and_push()  # warmup: compiles wave shapes
            t0 = time.perf_counter()
            for _ in range(unrolls):
                actor.unroll_and_push()
            dt = time.perf_counter() - t0
            return unrolls * T * pool.num_envs / dt
        finally:
            pool.close()
            set_trace_enabled(True)

    # Interleaved arms, best-of-3 (max filters OS scheduling noise on a
    # loaded box — same rationale as the telemetry section).
    on, off = [], []
    for _ in range(3):
        on.append(measure(True))
        off.append(measure(False))
    sps_on, sps_off = max(on), max(off)
    out = {
        "raw_ns_per_op": raw_ns,
        "recorder_capacity": get_recorder().capacity,
        "pool": f"{W}x{E} envs, T={T}, async, 1ms base delay",
        "env_steps_per_sec_on": round(sps_on, 1),
        "env_steps_per_sec_off": round(sps_off, 1),
        "overhead_pct": round((1.0 - sps_on / sps_off) * 100.0, 2),
    }
    log(f"bench: tracing overhead: {out['overhead_pct']}% "
        f"(on {out['env_steps_per_sec_on']} vs off "
        f"{out['env_steps_per_sec_off']} steps/s)")
    _history_append(
        "tracing",
        {"env_steps_per_sec_on": out["env_steps_per_sec_on"]},
        tiny=tiny,
    )
    return out


def run_bench_traj_ring(jax, tiny: bool = False) -> dict:
    """Zero-copy trajectory ring vs the queue path (ISSUE 3 tentpole):
    one VectorActor over fake Pong envs (84x84x4 uint8) feeding the real
    Learner batcher, fixed seeds, both data paths.

    Claims under test (the ISSUE 3 acceptance bound; asserted by
    tests/test_bench_units.py on the tiny variant):
    - batches are BIT-IDENTICAL between the two paths (same envs, same
      policy stream — the ring changes where bytes land, not what they
      are);
    - `telemetry/learner/host_stack_ms` drops (ring batches need no
      np.stack — the batcher hands slot views straight to device_put);
    - per-unroll enqueue copy bytes (`telemetry/learner/
      host_stack_bytes`, the bytes the stacking path copies) drop to 0.

    Honesty note: on backends where device_put can ALIAS host numpy (the
    jax CPU backend — this rig's test/fallback path), the ring stages
    each batch through ONE owning copy before transfer so slot recycling
    can't corrupt in-flight batches; those bytes are reported separately
    (`ring_stage_bytes_per_unroll`) and are 0 on copying-H2D production
    backends (TPU). Even staged, the ring is one copy per unroll fewer
    than the queue path (actor-private buffers + np.stack)."""
    import numpy as np
    import optax

    from torched_impala_tpu import configs
    from torched_impala_tpu.models import Agent, AtariShallowTorso, ImpalaNet
    from torched_impala_tpu.runtime import Learner, LearnerConfig, VectorActor
    from torched_impala_tpu.telemetry import Registry

    if tiny:
        T, E, B, n_batches = 4, 4, 4, 3
    else:
        T, E, B, n_batches = 20, 8, 8, 6
    cfg = configs.ExperimentConfig(
        name="bench_ring",
        env_family="atari",
        env_id="PongNoFrameskip-v4",
        obs_shape=(84, 84, 4),
        obs_dtype="uint8",
        num_actions=6,
    )
    factory = configs.make_env_factory(cfg, fake=True)
    agent = Agent(ImpalaNet(num_actions=6, torso=AtariShallowTorso()))
    try:
        device = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        device = None

    def measure(use_ring: bool):
        reg = Registry()  # isolated registry: per-arm telemetry deltas
        learner = Learner(
            agent=agent,
            optimizer=optax.rmsprop(6e-4, decay=0.99, eps=1e-7),
            config=LearnerConfig(
                batch_size=B,
                unroll_length=T,
                publish_interval=1_000_000,
                traj_ring=use_ring,
                # Host-copy measurement: the AOT layout compile is a
                # device-step concern and would dominate the wall time.
                auto_layouts=False,
            ),
            example_obs=configs.example_obs(cfg),
            rng=jax.random.key(0),
            telemetry=reg,
        )
        envs = [factory(1000 + j, j) for j in range(E)]
        actor = VectorActor(
            actor_id=0,
            envs=envs,
            agent=agent,
            param_store=learner.param_store,
            enqueue=learner.enqueue,
            unroll_length=T,
            seed=7,
            device=device,
            telemetry=reg,
            traj_ring=learner.traj_ring,
        )
        learner.start()
        batches = []
        t0 = time.perf_counter()
        try:
            for _ in range(n_batches):
                for _ in range(B // E):
                    actor.unroll_and_push()
                arrays, _, _ = learner._batch_q.get(timeout=300)
                # Owning copies: queued device arrays on the CPU backend
                # can be views whose buffers the allocator later reuses.
                batches.append(
                    jax.tree.map(lambda x: np.array(x, copy=True), arrays)
                )
            dt = time.perf_counter() - t0
        finally:
            learner.stop()
        snap = reg.snapshot()
        unrolls = n_batches * B
        entry = {
            "host_stack_ms": round(
                float(snap["telemetry/learner/host_stack_ms"]), 4
            ),
            "stack_copy_bytes_per_unroll": round(
                snap["telemetry/learner/host_stack_bytes"] / unrolls, 1
            ),
            "ring_stage_bytes_per_unroll": round(
                snap["telemetry/learner/ring_stage_bytes"] / unrolls, 1
            ),
            "batches_per_sec": round(n_batches / dt, 2),
        }
        if use_ring:
            entry["ring_occupancy"] = round(
                float(snap["telemetry/ring/occupancy"]), 3
            )
            entry["recycle_wait_ms_p95"] = round(
                float(snap["telemetry/ring/recycle_wait_ms_p95"]), 3
            )
        return entry, batches

    queue_entry, queue_batches = measure(False)
    ring_entry, ring_batches = measure(True)
    identical = True
    for bq, br in zip(queue_batches, ring_batches):
        for a, b in zip(jax.tree.leaves(bq), jax.tree.leaves(br)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                identical = False
    out = {
        "shapes": f"T={T} E={E} B={B} x {n_batches} batches, 84x84x4 uint8",
        "queue": queue_entry,
        "ring": ring_entry,
        "batches_bit_identical": identical,
        "host_stack_ms_ratio": round(
            ring_entry["host_stack_ms"]
            / max(queue_entry["host_stack_ms"], 1e-9),
            4,
        ),
    }
    log(f"bench: traj_ring: {out}")
    _history_append(
        "traj_ring",
        {"host_stack_ms_ratio": out["host_stack_ms_ratio"]},
        tiny=tiny,
        direction="lower",
    )
    return out


def run_bench_feed_path(jax, tiny: bool = False) -> dict:
    """Zero-copy feed path (ISSUE 13 tentpole): donated superbatch ring
    + overlapped H2D + the fused V-trace+loss epilogue, each against its
    pre-ISSUE baseline.

    Claims under test (asserted by tests/test_bench_units.py on the
    tiny variant; the full run's numbers feed the perfgate budgets):
    - with `donate_batch` the learner stages NOTHING through host
      memory (`learner/ring_stage_bytes` delta = 0 over the measured
      window) while the copying path stages every batch — and the
      superbatch ring is exercised PAST the old K=8 fused-dispatch
      ceiling (steps_per_dispatch=9 here);
    - the donated device_put overlaps the in-flight train step:
      `perf/h2d_ns_overlapped / perf/h2d_ns_total >= 0.8` over the
      steady-state window (the warmup step is excluded — its put pays
      the AOT compile and has no prior step to overlap with);
    - the fused epilogue's jitted value_and_grad step at a
      loss-dominated shape runs at <= 0.9x the separate path (measured
      ~0.73x at T=32 B=64 A=256 f32 on this box; the analytic VJP
      replaces XLA's backward through the shared log_softmax cube —
      see ops/vtrace_pallas.py's module docstring for why autodiff
      pessimizes there). f32 only: bf16 is software-emulated on CPU
      and would measure the emulation, not the epilogue."""
    import numpy as np
    import optax

    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.ops import losses as losses_lib
    from torched_impala_tpu.ops.losses import ImpalaLossConfig
    from torched_impala_tpu.runtime import Learner, LearnerConfig
    from torched_impala_tpu.telemetry import Registry

    # --- arm 1: donated superbatch ring vs the staging copy path ------
    # The torso is sized so one fused K-step dispatch computes for
    # several ms: H2D overlap is a property of a producer-rich feed
    # (the NEXT superbatch stages while the current step runs), which
    # only materializes when the step interval is wider than the put.
    K = 9  # one past the old K=8 fused ceiling, on purpose
    # warmup must outlast the batcher's maximum stage-ahead (device
    # queue depth + one in assembly): batches staged during the first
    # step's compile land before the counter snapshot.
    if tiny:
        T, B, warmup, n = 4, 4, 4, 10
    else:
        T, B, warmup, n = 8, 8, 4, 16
    A = 2
    agent = Agent(
        ImpalaNet(num_actions=A, torso=MLPTorso(hidden_sizes=(512, 512)))
    )
    rng = np.random.default_rng(0)
    # One superbatch sub-block of canned unroll data, memcpy'd into
    # every acquired ring block. A synthetic producer on purpose: on
    # this box a live VectorActor shares the core with the learner and
    # the system goes actor-bound — every put would land in an actor
    # window and the overlap number would measure the actor, not the
    # feed path. The writer below costs one memcpy per block, so the
    # learner stays saturated the way a multi-host actor fleet keeps it.
    canned = dict(
        obs=rng.normal(size=(T + 1, B, 4)).astype(np.float32),
        first=np.zeros((T + 1, B), np.bool_),
        actions=rng.integers(0, A, size=(T, B)).astype(np.int32),
        behaviour_logits=rng.normal(size=(T, B, A)).astype(np.float32),
        rewards=rng.normal(size=(T, B)).astype(np.float32),
        cont=np.ones((T, B), np.float32),
    )

    def measure_ring(donate: bool):
        reg = Registry()  # isolated registry: per-arm counter deltas
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-2),
            config=LearnerConfig(
                batch_size=B,
                unroll_length=T,
                publish_interval=1_000_000,
                traj_ring=True,
                steps_per_dispatch=K,
                donate_batch=donate,
            ),
            example_obs=np.zeros((4,), np.float32),
            rng=jax.random.key(0),
            telemetry=reg,
        )

        # Producer-rich drive: the feeder thread memcpys canned unrolls
        # into ring blocks flat out while this thread steps back to
        # back — the shape of a saturated deployment, where the
        # batcher's put of superbatch N+1 lands while step N computes.
        # (A lockstep push-then-step pattern measures ~0 overlap by
        # construction: every put lands in the gap between steps.)
        total = warmup + n
        marks = {}

        def feeder():
            from torched_impala_tpu.runtime.types import QueueClosed

            try:
                for _ in range(total * K):
                    blk = learner.traj_ring.acquire(B, lineage_id="bench")
                    for field, src in canned.items():
                        getattr(blk, field)[:] = src
                    blk.task[:] = 0
                    learner.traj_ring.commit(blk, 0, lineage_id="bench")
            except QueueClosed:
                pass

        # Synchronous dispatch for this arm: the learner scores each
        # put against the HOST-observed step window, which under CPU
        # async dispatch is just the enqueue (~us) — the compute runs
        # on XLA's pool after `step()` returns and no put can ever
        # intersect it. Sync dispatch makes the host window equal the
        # compute window, i.e. what the metric means on a real
        # accelerator (put vs in-flight device step).
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        learner.start()
        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        try:
            for i in range(total):
                if i == warmup:
                    # Steady-state counter window: everything before
                    # this snapshot (compile, the un-overlappable first
                    # put) is excluded from the deltas below.
                    marks["snap0"] = reg.snapshot()
                    marks["t0"] = time.perf_counter()
                learner.step_once(timeout=300)
            marks["dt"] = time.perf_counter() - marks["t0"]
            marks["snap1"] = reg.snapshot()
            th.join(timeout=600)
            assert not th.is_alive(), "feeder wedged"
        finally:
            learner.stop()
            jax.config.update("jax_cpu_enable_async_dispatch", True)
        snap0, snap1, dt = marks["snap0"], marks["snap1"], marks["dt"]

        def delta(name):
            return snap1.get(name, 0.0) - snap0.get(name, 0.0)

        h2d_total = delta("telemetry/perf/h2d_ns_total")
        h2d_over = delta("telemetry/perf/h2d_ns_overlapped")
        return {
            "stage_bytes_per_batch": round(
                delta("telemetry/learner/ring_stage_bytes") / n, 1
            ),
            "donated_batches": int(
                delta("telemetry/learner/donated_batches")
            ),
            "h2d_ms_total": round(h2d_total / 1e6, 3),
            "h2d_overlap_frac": round(
                h2d_over / h2d_total if h2d_total else 0.0, 4
            ),
            "steps_per_sec": round(n / dt, 2),
        }

    copy_entry = measure_ring(donate=False)
    donated_entry = measure_ring(donate=True)

    # --- arm 2: fused vs separate epilogue at a loss-dominated shape --
    if tiny:
        Tl, Bl, A, reps = 16, 16, 128, 5
    else:
        Tl, Bl, A, reps = 32, 64, 256, 20
    rng = np.random.default_rng(0)
    inputs = dict(
        target_logits=jnp_f32(jax, rng.normal(size=(Tl, Bl, A))),
        behaviour_logits=jnp_f32(jax, rng.normal(size=(Tl, Bl, A))),
        values=jnp_f32(jax, rng.normal(size=(Tl, Bl))),
        bootstrap_value=jnp_f32(jax, rng.normal(size=(Bl,))),
        actions=jax.numpy.asarray(rng.integers(0, A, size=(Tl, Bl))),
        rewards=jnp_f32(jax, rng.normal(size=(Tl, Bl))),
        discounts=jnp_f32(jax, np.full((Tl, Bl), 0.99)),
        mask=jnp_f32(jax, (rng.random((Tl, Bl)) > 0.2)),
    )

    def step_ms(fused: bool) -> float:
        config = ImpalaLossConfig(fused_epilogue=fused)

        def f(tl, v):
            out = losses_lib.impala_loss(
                **{**inputs, "target_logits": tl, "values": v},
                config=config,
            )
            return out.total, out.logs

        g = jax.jit(jax.value_and_grad(f, argnums=(0, 1), has_aux=True))
        args = (inputs["target_logits"], inputs["values"])
        jax.block_until_ready(g(*args))  # compile
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(g(*args))
            times.append((time.perf_counter() - t0) * 1e3)
        return statistics.median(times)

    sep_ms = step_ms(fused=False)
    fused_ms = step_ms(fused=True)
    ratio = round(fused_ms / sep_ms, 4)

    out = {
        "ring_shapes": f"K={K} T={T} B={B} x {n} steps (+{warmup} warmup)",
        "superbatch_k": K,
        "copy": copy_entry,
        "donated": donated_entry,
        "loss_shape": f"T={Tl} B={Bl} A={A} f32 x {reps} reps",
        "separate_step_ms": round(sep_ms, 3),
        "fused_step_ms": round(fused_ms, 3),
        "fused_epilogue_step_ratio": ratio,
    }
    log(f"bench: feed_path: {out}")
    _history_append(
        "feed_path",
        {"h2d_overlap_frac": donated_entry["h2d_overlap_frac"]},
        tiny=tiny,
        direction="higher",
    )
    _history_append(
        "feed_path",
        {"fused_epilogue_step_ratio": ratio},
        tiny=tiny,
        direction="lower",
    )
    return out


def run_bench_mesh_feed(jax, tiny: bool = False) -> dict:
    """Mesh-native zero-copy feed (ISSUE 15 tentpole): sharded
    superbatch placement straight from ring slots on a 2-device CPU
    mesh, vs the reshard-hop baseline the mesh learner used to take.

    Claims under test (tiny variant asserted by tests/test_bench_units
    .py; the full run's numbers feed the perfgate budgets):
    - the donated mesh ring learner stages ZERO bytes host-side over
      the measured window (`mesh_ring_stage_bytes`, budget max 0) while
      training end-to-end with per-shard H2D telemetry populated;
    - per-batch sharded placement (one device_put per shard, sliced
      from the host buffer) is no slower than the explicit
      stage-on-one-device-then-reshard hop it replaces
      (`mesh_feed_step_ratio` = direct/reshard, budget max 1.0 — the
      hop moves every byte twice)."""
    import numpy as np
    import optax

    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.parallel import make_mesh, multihost, spec_layout
    from torched_impala_tpu.runtime import Learner, LearnerConfig
    from torched_impala_tpu.telemetry import Registry

    devices = jax.devices("cpu")
    if len(devices) < 2:
        return {"skipped": "needs >= 2 CPU devices (XLA_FLAGS "
                           "--xla_force_host_platform_device_count)"}
    mesh = make_mesh(num_data=2, devices=devices[:2])

    # --- arm 1: donated mesh ring learner, staged bytes must be 0 -----
    if tiny:
        T, B, warmup, n = 4, 4, 2, 6
    else:
        T, B, warmup, n = 8, 8, 4, 12
    A = 2
    agent = Agent(
        ImpalaNet(num_actions=A, torso=MLPTorso(hidden_sizes=(64, 64)))
    )
    rng = np.random.default_rng(0)
    canned = dict(
        obs=rng.normal(size=(T + 1, B, 4)).astype(np.float32),
        first=np.zeros((T + 1, B), np.bool_),
        actions=rng.integers(0, A, size=(T, B)).astype(np.int32),
        behaviour_logits=rng.normal(size=(T, B, A)).astype(np.float32),
        rewards=rng.normal(size=(T, B)).astype(np.float32),
        cont=np.ones((T, B), np.float32),
    )
    reg = Registry()
    learner = Learner(
        agent=agent,
        optimizer=optax.sgd(1e-2),
        config=LearnerConfig(
            batch_size=B,
            unroll_length=T,
            publish_interval=1_000_000,
            traj_ring=True,
            donate_batch=True,
        ),
        example_obs=np.zeros((4,), np.float32),
        rng=jax.random.key(0),
        telemetry=reg,
        mesh=mesh,
    )
    total = warmup + n
    marks = {}

    def feeder():
        from torched_impala_tpu.runtime.types import QueueClosed

        try:
            for _ in range(total):
                blk = learner.traj_ring.acquire(B, lineage_id="bench")
                for field, src in canned.items():
                    getattr(blk, field)[:] = src
                blk.task[:] = 0
                learner.traj_ring.commit(blk, 0, lineage_id="bench")
        except QueueClosed:
            pass

    learner.start()
    th = threading.Thread(target=feeder, daemon=True)
    th.start()
    try:
        for i in range(total):
            if i == warmup:
                marks["snap0"] = reg.snapshot()
            learner.step_once(timeout=300)
        marks["snap1"] = reg.snapshot()
        th.join(timeout=600)
        assert not th.is_alive(), "feeder wedged"
    finally:
        learner.stop()
    snap0, snap1 = marks["snap0"], marks["snap1"]

    def delta(name):
        return snap1.get(name, 0.0) - snap0.get(name, 0.0)

    mesh_stage_bytes = delta("telemetry/learner/ring_stage_bytes")
    donated = int(delta("telemetry/learner/donated_batches"))
    h2d_total = delta("telemetry/perf/h2d_ns_total")

    # --- arm 2: per-batch placement, direct per-shard vs reshard hop --
    # The hop is what the mesh learner used to do implicitly: land the
    # whole batch on ONE device, then reshard to the data layout —
    # every byte crosses H2D twice. Direct placement slices the host
    # buffer per shard and puts each slice once.
    if tiny:
        Tp, Bp, reps = 16, 32, 5
    else:
        Tp, Bp, reps = 64, 128, 20
    host = rng.normal(size=(Tp + 1, Bp, 64)).astype(np.float32)
    sh = spec_layout.feed_shardings(mesh)[0]  # obs: [T+1, B, ...]

    def time_put(put):
        times = []
        for _ in range(reps + 1):
            t0 = time.perf_counter()
            jax.block_until_ready(put())
            times.append((time.perf_counter() - t0) * 1e3)
        return statistics.median(times[1:])  # drop the warmup rep

    direct_ms = time_put(lambda: multihost.place_batch(sh, host))

    def reshard_hop():
        staged = jax.device_put(host, devices[0])
        return jax.device_put(staged, sh)

    reshard_ms = time_put(reshard_hop)
    ratio = round(direct_ms / reshard_ms, 4)

    out = {
        "ring_shapes": f"T={T} B={B} x {n} steps (+{warmup} warmup), "
                       "2-device data mesh",
        "mesh_ring_stage_bytes": float(mesh_stage_bytes),
        "donated_batches": donated,
        "h2d_ms_total": round(h2d_total / 1e6, 3),
        "placement_shape": f"[{Tp + 1}, {Bp}, 64] f32 x {reps} reps",
        "direct_place_ms": round(direct_ms, 3),
        "reshard_hop_ms": round(reshard_ms, 3),
        "mesh_feed_step_ratio": ratio,
    }
    log(f"bench: mesh_feed: {out}")
    _history_append(
        "mesh_feed",
        {
            "mesh_ring_stage_bytes": float(mesh_stage_bytes),
            "mesh_feed_step_ratio": ratio,
        },
        tiny=tiny,
        direction="lower",
    )
    return out


def jnp_f32(jax, x):
    return jax.numpy.asarray(x, dtype=jax.numpy.float32)


def run_bench_replay(jax, tiny: bool = False) -> dict:
    """IMPACT replay on the trajectory ring (ISSUE 9 tentpole): the same
    fresh unroll stream drives two learners — replay off vs
    ReplayConfig(max_reuse=2) — and the replay arm must deliver >= 1.8x
    the SGD updates per env frame (each committed slot is re-delivered
    once through the clipped-target surrogate) at equal env throughput.

    Claims under test (asserted by tests/test_bench_units.py on the tiny
    variant):
    - `updates_per_env_frame_multiplier` >= 1.8 (the acceptance bound;
      exactly 2.0 when nothing expires or evicts);
    - per-update step cost stays within a loose overhead bound of the
      plain path (`update_ms_ratio` — the surrogate adds one extra
      target-policy unroll forward, not an extra order of magnitude);
    - every replayed batch really went through the surrogate
      (`replay/reuse_delivered` == n_batches).
    """
    import queue as queue_mod

    import numpy as np
    import optax

    from torched_impala_tpu.envs.fake import ScriptedEnv
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.replay import ReplayConfig
    from torched_impala_tpu.runtime import Learner, LearnerConfig, VectorActor
    from torched_impala_tpu.telemetry import Registry

    if tiny:
        T, E, B, n_batches = 4, 4, 4, 3
    else:
        T, E, B, n_batches = 16, 8, 8, 8
    agent = Agent(
        ImpalaNet(num_actions=2, torso=MLPTorso(hidden_sizes=(32,)))
    )

    def measure(replay):
        reg = Registry()  # isolated registry: per-arm telemetry deltas
        learner = Learner(
            agent=agent,
            optimizer=optax.sgd(1e-2),
            config=LearnerConfig(
                batch_size=B,
                unroll_length=T,
                publish_interval=1,
                traj_ring=True,
                replay=replay,
                auto_layouts=False,
            ),
            example_obs=np.zeros((4,), np.float32),
            rng=jax.random.key(0),
            telemetry=reg,
        )
        envs = [ScriptedEnv(episode_len=5) for _ in range(E)]
        actor = VectorActor(
            actor_id=0,
            envs=envs,
            agent=agent,
            param_store=learner.param_store,
            enqueue=learner.enqueue,
            unroll_length=T,
            seed=7,
            telemetry=reg,
            traj_ring=learner.traj_ring,
        )
        learner.start()
        updates = 0
        t0 = time.perf_counter()
        try:
            # Interleave pushes with steps so neither the ring nor the
            # device queue ever backs up into a blocked actor.
            for _ in range(n_batches):
                for _ in range(B // E):
                    actor.unroll_and_push()
                try:
                    learner.step_once(timeout=60)
                    updates += 1
                except queue_mod.Empty:
                    pass
            while True:  # drain the replay tail
                try:
                    learner.step_once(timeout=2.0)
                    updates += 1
                except queue_mod.Empty:
                    break
            dt = time.perf_counter() - t0
        finally:
            learner.stop()
        snap = reg.snapshot()
        env_frames = n_batches * B * T  # identical in both arms
        entry = {
            "updates": updates,
            "env_frames": env_frames,
            "updates_per_env_frame": round(updates / env_frames, 6),
            "update_ms": round(dt * 1e3 / max(updates, 1), 3),
            "reuse_delivered": int(
                snap.get("telemetry/replay/reuse_delivered", 0)
            ),
            "target_updates": int(
                snap.get("telemetry/replay/target_updates", 0)
            ),
            "evict_pressure": int(
                snap.get("telemetry/replay/evict_pressure", 0)
            ),
        }
        return entry

    off = measure(None)
    on = measure(ReplayConfig(max_reuse=2, target_update_interval=4))
    out = {
        "shapes": f"T={T} E={E} B={B} x {n_batches} fresh batches, MLP",
        "off": off,
        "on": on,
        "updates_per_env_frame_multiplier": round(
            on["updates_per_env_frame"]
            / max(off["updates_per_env_frame"], 1e-12),
            3,
        ),
        "update_ms_ratio": round(
            on["update_ms"] / max(off["update_ms"], 1e-9), 3
        ),
    }
    log(f"bench: replay: {out}")
    _history_append(
        "replay",
        {
            "updates_per_env_frame_multiplier": out[
                "updates_per_env_frame_multiplier"
            ]
        },
        tiny=tiny,
    )
    return out


def run_bench_chaos(jax, tiny: bool = False) -> dict:
    """Resilience chaos bench (ISSUE 5 tentpole acceptance): inject the
    fault plan {SIGKILL one env worker, crash one actor thread, crash the
    learner mid-run} into a checkpointed training run, then prove the
    system's recovery claims with numbers:

    - the run dies at the injected learner crash WITHOUT a final save;
      `--resume auto` restores the newest manifest and training reaches
      the original target step count (`recovered`);
    - lost progress is bounded by the checkpoint interval
      (`lost_steps <= interval`);
    - two resumes of the same manifest produce BIT-IDENTICAL first
      post-recovery batches on fixed seeds (the determinism story of
      utils/checkpoint.py extended through crash recovery);
    - async checkpointing at a production cadence adds <1% to learner
      steps/sec (`checkpoint_overhead_pct`: the per-save wall cost from
      an every-step STRESS arm, amortized over a 100-step interval —
      10x denser than the presets' default 1000; the train loop hands
      the writer an on-device clone and never blocks on disk).

    tests/test_bench_units.py asserts the tiny variant with CI slack."""
    import dataclasses
    import shutil
    import tempfile

    import numpy as np
    import optax

    from torched_impala_tpu import configs
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.resilience import (
        AsyncCheckpointer,
        ChaosError,
        ChaosPlan,
        config_fingerprint,
        restore_latest,
    )
    from torched_impala_tpu.runtime import Learner, LearnerConfig, VectorActor
    from torched_impala_tpu.runtime.loop import train
    from torched_impala_tpu.telemetry import Registry

    cfg = configs.CARTPOLE
    agent = configs.make_agent(cfg)
    factory = configs.make_env_factory(cfg, fake=True)
    lcfg = dataclasses.replace(configs.make_learner_config(cfg), batch_size=2)
    fp = config_fingerprint(cfg)
    if tiny:
        target, crash_at, interval, overhead_steps = 8, 4, 2, 30
    else:
        target, crash_at, interval, overhead_steps = 30, 12, 3, 200
    ckdir = tempfile.mkdtemp(prefix="bench_chaos_")
    out: dict = {
        "fault_plan": [
            {"kind": "kill_env_worker", "at": 4, "target": 0},
            {"kind": "raise_in_actor", "at": 3},
            {"kind": "crash_learner", "at": crash_at},
        ],
        "target_steps": target,
        "checkpoint_interval": interval,
    }
    common = dict(
        agent=agent,
        env_factory=factory,
        example_obs=configs.example_obs(cfg),
        num_actors=2,
        learner_config=lcfg,
        optimizer=configs.make_optimizer(cfg),
        seed=0,
        log_every=1,
        config_hash=fp,
    )
    try:
        # -- run 1: faults armed, dies at the injected learner crash ----
        ck = AsyncCheckpointer(
            ckdir, keep=3, interval_steps=interval, config_hash=fp
        )
        from torched_impala_tpu.resilience import ChaosInjector

        injector = ChaosInjector(ChaosPlan.from_dicts(out["fault_plan"]))
        crashed = False
        try:
            train(
                total_steps=target,
                async_checkpointer=ck,
                chaos=injector,
                actor_mode="process",
                envs_per_actor=2,
                **common,
            )
        except ChaosError:
            crashed = True
        ck.wait()
        saved = ck.all_steps()
        ck.close()
        out["crashed_as_injected"] = crashed
        out["crash_step"] = crash_at
        out["saved_steps"] = saved
        # Every armed fault fired, and the learner still reached the
        # crash step — i.e. the worker SIGKILL and the actor crash were
        # absorbed by the pool repair / supervisor BEFORE the injected
        # learner death ended the run.
        out["faults_fired"] = sorted(f.kind for f in injector.fired)

        # -- post-recovery determinism: resume the SAME manifest twice,
        # the first assembled batch must be bit-identical -------------
        def first_batch_after_resume():
            reg = Registry()
            learner = Learner(
                agent=agent,
                optimizer=configs.make_optimizer(cfg),
                config=lcfg,
                example_obs=configs.example_obs(cfg),
                rng=jax.random.key(0),
                telemetry=reg,
            )
            manifest, state = restore_latest(
                ckdir, learner.get_state(), config_hash=fp
            )
            learner.set_state(state)
            actor = VectorActor(
                actor_id=0,
                envs=[factory(1000 + j, j) for j in range(2)],
                agent=agent,
                param_store=learner.param_store,
                enqueue=learner.enqueue,
                unroll_length=lcfg.unroll_length,
                seed=7,
                telemetry=reg,
            )
            learner.start()
            try:
                actor.unroll_and_push()
                arrays, version, _ = learner._batch_q.get(timeout=300)
                return (
                    manifest.step,
                    jax.tree.map(
                        lambda x: np.array(x, copy=True), arrays
                    ),
                )
            finally:
                learner.stop()

        step_a, batch_a = first_batch_after_resume()
        step_b, batch_b = first_batch_after_resume()
        identical = step_a == step_b and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(batch_a), jax.tree.leaves(batch_b))
        )
        out["resumed_from_step"] = step_a
        out["lost_steps"] = crash_at - step_a
        out["post_recovery_batches_bit_identical"] = identical

        # -- run 2: --resume auto back to the full target --------------
        ck2 = AsyncCheckpointer(
            ckdir, keep=3, interval_steps=interval, config_hash=fp
        )
        result = train(
            total_steps=target,
            async_checkpointer=ck2,
            resume="auto",
            **common,
        )
        ck2.close()
        out["final_steps"] = result.learner.num_steps
        out["actor_restarts"] = result.actor_restarts
        out["recovered"] = result.learner.num_steps == target
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)

    # -- checkpoint overhead on the learner step loop -------------------
    def steps_per_sec(ck: "AsyncCheckpointer | None") -> float:
        learner = Learner(
            agent=Agent(ImpalaNet(num_actions=2, torso=MLPTorso())),
            optimizer=optax.rmsprop(6e-4, decay=0.99, eps=1e-7),
            config=LearnerConfig(batch_size=2, unroll_length=5),
            example_obs=np.zeros((4,), np.float32),
            rng=jax.random.key(0),
            telemetry=Registry(),
        )
        envs = [factory(2000 + j, j) for j in range(2)]
        actor = VectorActor(
            actor_id=0,
            envs=envs,
            agent=learner._agent,
            param_store=learner.param_store,
            enqueue=learner.enqueue,
            unroll_length=5,
            seed=11,
            telemetry=Registry(),
        )
        if ck is not None:
            learner.post_step = lambda n: ck.maybe_save(
                n, learner.get_state_device, param_version=learner.num_frames
            )
        learner.start()
        try:
            for _ in range(3):  # warm the jits out of the timed window
                actor.unroll_and_push()
                learner.step_once(timeout=300)
            t0 = time.perf_counter()
            for _ in range(overhead_steps):
                actor.unroll_and_push()
                learner.step_once(timeout=300)
            dt = time.perf_counter() - t0
        finally:
            learner.stop()
        return overhead_steps / dt

    sps_off = steps_per_sec(None)
    ovdir = tempfile.mkdtemp(prefix="bench_chaos_ov_")
    try:
        # interval_steps=1 = a save attempt after EVERY learner step — a
        # deliberate STRESS arm, ~100-1000x the production cadence
        # (presets default checkpoint_interval=1000), so the per-save
        # cost is measurable above timer noise. On this 1-core box the
        # background writer contends with the learner for the only core
        # (fsync x3 files + zip per save), so the stress number is an
        # upper bound no multi-core host approaches.
        ck = AsyncCheckpointer(ovdir, keep=2, interval_steps=1)
        sps_on = steps_per_sec(ck)
        ck.wait()
        saves = ck.saves
        ck.close()
    finally:
        shutil.rmtree(ovdir, ignore_errors=True)
    out["steps_per_sec_off"] = round(sps_off, 2)
    out["steps_per_sec_on_every_step"] = round(sps_on, 2)
    out["overhead_saves"] = saves
    out["overhead_pct_every_step"] = round(
        (sps_off - sps_on) / sps_off * 100.0, 3
    )
    # The acceptance number: overhead at a production cadence. The
    # every-step stress arm yields the full per-save wall cost (capture +
    # write + fsync + contention); amortized over a 100-step interval —
    # 10x DENSER than the presets' default of 1000 — it must sit below
    # 1% of learner throughput.
    per_save_s = max(0.0, 1.0 / sps_on - 1.0 / sps_off)
    out["per_save_cost_ms"] = round(per_save_s * 1e3, 3)
    out["checkpoint_overhead_pct"] = round(
        per_save_s / (100.0 / sps_off) * 100.0, 4
    )
    log(f"bench: chaos: {out}")
    _history_append(
        "chaos", {"steps_per_sec_off": out["steps_per_sec_off"]}, tiny=tiny
    )
    return out


def run_bench_multihost(
    jax, tiny: bool = False, chaos_arm: bool = True
) -> dict:
    """Multi-host pod-slice bench (ISSUE 18 acceptance): weak-scaling
    efficiency of the simulated cluster, all-reduce overlap, and the
    kill_host chaos recovery scenario.

    Every arm launches REAL multi-process clusters (parallel/simhost.py:
    each child is its own jax controller pinned to the CPU backend with
    gloo collectives — also on TPU boxes, so the numbers are
    backend-stable and the rows append under the cpu fingerprint).

    - Weak scaling: each host carries the SAME local load (local batch,
      actor fleet, straggler-paced envs); a perfect pod doubles global
      frames/s when hosts double. Envs sleep `env_delay_s` per step so
      env pacing — not the single shared CPU core — dominates the step,
      which is what lets two simulated hosts interleave on a 1-core box
      at all (real pods give each host its own cores; this arm measures
      the harness's coordination overhead, not CPU contention). Two
      measurement traps, both fixed by construction: (a) actors bank
      unrolls in the feed queue while step 1 compiles, and a short run's
      "steady" window then measures queue DRAIN speed, not paced
      production — so the arms cap actor lead (queue_capacity override)
      and take the window over the run's SECOND HALF (log_every =
      steps//2 puts exactly two log calls at steps//2 and steps, long
      after the backlog is gone); (b) each log call materializes device
      scalars (a sync), and on a contended 1-core box any sync can eat a
      scheduler-quantum stall that debits the overlap gauge — two sync
      sites bound that debit at 2 steps' worth of estimate.
      `multihost_weak_scaling_eff = fps(2 hosts, global 2B) /
      (2 * fps(1 host, global B))`, budget min 0.8.
    - `allreduce_overlap_frac` (min over hosts of the 2-host run's
      perf/allreduce_overlap_frac gauge): the fraction of the ring
      all-reduce cost-model estimate the learner hid behind the step,
      budget min 0.8.
    - kill_host chaos: a 2-host checkpointed run on the learnable
      VectorSignalEnv with the traj_ring feed; the fault SIGKILLs host 1
      mid-ring-commit, the launcher reaps the corpse and kills the
      blocked survivor, `launch_with_recovery` relaunches with
      resume=True and the plan disarmed, and the resumed run must reach
      the target step count AND the return target (the run still LEARNS
      after losing a host, not merely steps).

    tests/test_bench_units.py asserts the tiny variant with
    `chaos_arm=False` — the kill_host recovery scenario is pinned
    end-to-end by tests/test_multihost.py already, and two extra cluster
    relaunches inside the tier-1 wall-clock budget buy no new
    coverage."""
    import shutil
    import tempfile

    from torched_impala_tpu.runtime import distributed

    if tiny:
        steps, b_local, T, delay = 20, 2, 4, 0.015
        chaos_steps, return_target = 30, 5.0
    else:
        steps, b_local, T, delay = 30, 4, 5, 0.02
        chaos_steps, return_target = 60, 6.0

    out: dict = {"hosts": 2, "local_batch": b_local, "steps": steps}

    # -- weak scaling + allreduce overlap -------------------------------
    base = dict(
        devices_per_host=1,
        total_steps=steps,
        unroll_length=T,
        num_actors=1,
        envs_per_actor=b_local,
        seed=3,
        env_delay_s=delay,
        # Two log calls (steps//2, steps): the steady window is the paced
        # second half, and sync-stall debits against the overlap gauge
        # are bounded at two steps' estimate (see docstring).
        log_every=steps // 2,
        # One batch of actor lead: the compile-time backlog drains within
        # a couple of steps instead of masking paced production.
        learner_overrides={"queue_capacity": b_local},
    )
    one = distributed.DistSpec(num_hosts=1, batch_size=b_local, **base)
    two = distributed.DistSpec(num_hosts=2, batch_size=2 * b_local, **base)
    res1 = distributed.launch_cluster(one, timeout=240)
    if not res1.ok:
        raise RuntimeError(f"1-host arm failed: {res1.describe()}")
    res2 = distributed.launch_cluster(two, timeout=240)
    if not res2.ok:
        raise RuntimeError(f"2-host arm failed: {res2.describe()}")
    p1 = res1.hosts[0].results()[-1]
    p2 = [h.results()[-1] for h in res2.hosts]
    fps1 = p1["steady_frames_per_s"] or 0.0
    # Both controllers report the same global program; min = the slower
    # controller's view of it (conservative).
    fps2 = min(p["steady_frames_per_s"] or 0.0 for p in p2)
    eff = fps2 / (2.0 * fps1) if fps1 > 0 else 0.0
    overlap = min(
        (p["allreduce_overlap_frac"] or 0.0) for p in p2
    )
    out["fps_1host"] = fps1
    out["fps_2host"] = fps2
    out["multihost_weak_scaling_eff"] = round(eff, 4)
    out["allreduce_overlap_frac"] = round(overlap, 4)
    out["allreduce_ns_total"] = p2[0].get("allreduce_ns_total")

    # -- kill_host chaos recovery ---------------------------------------
    if not chaos_arm:
        log(f"bench: multihost: {out}")
        _history_append(
            "multihost",
            {
                "multihost_weak_scaling_eff": out[
                    "multihost_weak_scaling_eff"
                ],
                "allreduce_overlap_frac": out["allreduce_overlap_frac"],
            },
            tiny=tiny,
            backend="cpu",  # the simulated pod is CPU-by-construction
        )
        return out
    ckdir = tempfile.mkdtemp(prefix="bench_multihost_")
    try:
        chaos_spec = distributed.DistSpec(
            num_hosts=2,
            devices_per_host=1,
            total_steps=chaos_steps,
            batch_size=4,
            unroll_length=5,
            num_actors=1,
            envs_per_actor=2,
            seed=11,
            env="signal",
            num_actions=2,
            episode_len=8,
            optimizer="adam",
            learning_rate=1e-2,
            entropy_cost=0.001,
            learner_overrides={"traj_ring": True},
            checkpoint_dir=ckdir,
            checkpoint_interval=2,
            chaos=[{"kind": "kill_host", "at": 3}],
            chaos_host=1,
        )
        final, attempts = distributed.launch_with_recovery(
            chaos_spec, max_restarts=2, timeout=300
        )
        out["chaos_attempts"] = len(attempts)
        out["chaos_first_attempt_died"] = not attempts[0].ok
        out["chaos_recovered"] = final.ok
        if final.ok:
            payloads = [h.results()[-1] for h in final.hosts]
            out["chaos_final_steps"] = max(p["steps"] for p in payloads)
            tails = [
                p["episode_return_mean_tail"]
                for p in payloads
                if p.get("episode_return_mean_tail") is not None
            ]
            out["chaos_return_tail"] = (
                round(max(tails), 3) if tails else None
            )
            out["chaos_reached_return_target"] = bool(
                tails and max(tails) >= return_target
            )
            out["chaos_return_target"] = return_target
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)

    log(f"bench: multihost: {out}")
    _history_append(
        "multihost",
        {
            "multihost_weak_scaling_eff": out["multihost_weak_scaling_eff"],
            "allreduce_overlap_frac": out["allreduce_overlap_frac"],
        },
        tiny=tiny,
        backend="cpu",  # the simulated pod is CPU-by-construction
    )
    return out


def run_bench_serving(jax, tiny: bool = False) -> dict:
    """Serving-tier bench (ISSUE 6 acceptance): coalesced continuous
    batching vs per-request inference at 64 concurrent clients, shadow
    traffic cost, and the bf16 greedy-parity gate.

    Protocol: 64 clients drive the SAME PolicyServer surface in rounds —
    every client submits one async request, then all responses are
    awaited (one driver thread models the concurrent fleet without
    spawning 64 OS threads on a 1-core box; the server sees 64
    simultaneously-outstanding requests either way, which is what
    coalescing batches over). Arms:
      per_request: max_batch=1 — every request is its own wave (the
        per-actor-inference shape the serving tier replaces);
      coalesced:   max_batch=64 — one padded wave per round;
      shadow:      coalesced + a shadow label scoring every sampled wave
        on the best-effort background thread (actions logged, never
        returned — drop-when-busy keeps the primary path unblocked).

    Claims pinned by tests/test_bench_units.py on the tiny variant:
    coalesced >= 3x per-request aggregate actions/s; shadow latency
    overhead on primary waves bounded (<= 5% is the artifact target on
    an idle multi-core host; the CI assert keeps 1-core/GIL slack, same
    convention as the chaos/tracing sections); bf16 greedy parity holds.
    """
    import numpy as np

    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.serving import (
        InProcessClient,
        PolicyServer,
        VersionRegistry,
        greedy_action_parity,
    )
    from torched_impala_tpu.telemetry import Registry

    C = 64  # concurrent clients (the acceptance-criteria fleet size)
    rounds = 4 if tiny else 30
    obs_dim = 8
    agent = Agent(
        ImpalaNet(num_actions=6, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((obs_dim,), np.float32)
    )
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(C, obs_dim)).astype(np.float32)

    def measure(max_batch: int, shadow: bool):
        reg = Registry()
        store = ParamStore()
        store.publish(0, params)
        registry = VersionRegistry(store, telemetry=reg)
        registry.pin("live", 0)
        if shadow:
            # Same params under a second label: the cost arm measures
            # shadow COMPUTE, not a different policy.
            registry.pin("shadow", 0)
            registry.set_routing(
                {"live": 1.0}, shadow="shadow", shadow_fraction=1.0
            )
        else:
            registry.set_routing({"live": 1.0})
        server = PolicyServer(
            agent=agent,
            registry=registry,
            example_obs=np.zeros((obs_dim,), np.float32),
            max_clients=C,
            max_batch=max_batch,
            max_wait_s=5e-3,
            telemetry=reg,
        ).start()
        try:
            clients = [InProcessClient(server, greedy=True)
                       for _ in range(C)]
            def round_trip(first: bool) -> None:
                cells = [
                    c.act_async(obs[i], first)
                    for i, c in enumerate(clients)
                ]
                for cell in cells:
                    cell.result(timeout=120.0)
            round_trip(True)  # warmup: compiles the wave shape
            t0 = time.perf_counter()
            for _ in range(rounds):
                round_trip(False)
            dt = time.perf_counter() - t0
            for c in clients:
                c.close()
        finally:
            server.close()
        snap = reg.snapshot()
        return {
            "actions_per_sec": round(C * rounds / dt, 1),
            "wave_ms_p50": round(
                float(snap["telemetry/serving/wave_ms_p50"]), 3
            ),
            "wave_ms_p95": round(
                float(snap["telemetry/serving/wave_ms_p95"]), 3
            ),
            "waves": int(snap["telemetry/serving/wave_total"]),
            "wave_size_p50": round(
                float(snap["telemetry/serving/wave_size_p50"]), 1
            ),
            "shadow_scored": int(snap["telemetry/serving/shadow_total"]),
            "shadow_skipped": int(
                snap["telemetry/serving/shadow_skipped"]
            ),
            "shadow_mismatches": int(
                snap["telemetry/serving/shadow_mismatch"]
            ),
        }

    per_request = measure(max_batch=1, shadow=False)
    coalesced = measure(max_batch=C, shadow=False)
    shadowed = measure(max_batch=C, shadow=True)
    parity_ok, mismatches = greedy_action_parity(agent, params, obs)
    out = {
        "clients": C,
        "rounds": rounds,
        "per_request": per_request,
        "coalesced": coalesced,
        "shadow": shadowed,
        "coalesced_speedup": round(
            coalesced["actions_per_sec"]
            / max(per_request["actions_per_sec"], 1e-9),
            2,
        ),
        "shadow_latency_overhead_pct": round(
            (
                shadowed["wave_ms_p50"]
                / max(coalesced["wave_ms_p50"], 1e-9)
                - 1.0
            )
            * 100.0,
            2,
        ),
        "shadow_throughput_overhead_pct": round(
            (
                1.0
                - shadowed["actions_per_sec"]
                / max(coalesced["actions_per_sec"], 1e-9)
            )
            * 100.0,
            2,
        ),
        "bf16_parity": parity_ok,
        "bf16_mismatches": mismatches,
    }
    log(
        f"bench: serving: {out['coalesced_speedup']}x coalesced vs "
        f"per-request at {C} clients "
        f"({coalesced['actions_per_sec']} vs "
        f"{per_request['actions_per_sec']} actions/s), shadow latency "
        f"+{out['shadow_latency_overhead_pct']}%, bf16 parity "
        f"{parity_ok}"
    )
    _history_append(
        "serving", {"coalesced_speedup": out["coalesced_speedup"]}, tiny=tiny
    )
    return out


def run_bench_loadgen(jax, tiny: bool = False) -> dict:
    """Fleet serving under open-loop load (ISSUE 14 acceptance): with
    draining version rollouts happening UNDER live traffic, a 2-replica
    ServingFleet must sustain higher goodput (within-SLO completions/s)
    than a single replica at the same offered Poisson rate and the same
    p99 SLO budget — and every rollout must complete with zero
    dropped/errored requests on both arms. A separate failover scenario
    kills one server mid-wave via the chaos harness; the router must
    absorb it with zero failed requests.

    Why an incident window is the arena: on a single-CPU box two
    replicas add no raw compute, so a steady-state throughput race
    measures ~1.0x by construction (verified: closed-loop capacity is
    0.9-1.03x across net sizes). What a fleet buys is AVAILABILITY.
    Both arms serve int8 (the parity-gated quantized path this PR
    adds) under the same open-loop Poisson stream, with a draining
    rollout every `deploy_every_s` for the whole window (compressing a
    deploy-heavy day the way the diurnal shape compresses a day into
    `period_s`) — and at the midpoint arrival the chaos harness kills
    one server mid-wave. The single arm has nowhere to fail over:
    every later request errors, and its goodput is capped at half the
    window. The fleet arm marks the replica dead, retries the
    in-flight requests exactly once on the survivor, keeps absorbing
    rollouts, and finishes with ZERO failed requests.

    Claims pinned by tests/test_bench_units.py (tiny) and by
    tools/perfgate.py budgets on the full run's BENCH_HISTORY.jsonl
    records: fleet_goodput_ratio >= the pinned floor, fleet p99 under
    the SLO budget with zero failed requests, failover run has
    failed == 0 with retried >= 1 and exactly one dead replica."""
    import numpy as np

    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.resilience.chaos import (
        ChaosInjector,
        ChaosPlan,
        Fault,
    )
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.serving import (
        InProcessClient,
        ServingFleet,
        TrafficShape,
        greedy_action_parity,
        run_load,
    )
    from torched_impala_tpu.serving.fleet import DEAD
    from torched_impala_tpu.telemetry import Registry

    obs_dim = 8
    slo_ms = 50.0
    clients = 16 if tiny else 32
    dur_s = 2.0 if tiny else 6.0
    calib_s = 0.8 if tiny else 2.0
    deploy_every_s = 0.15
    # int8 serving — the production-shaped quantized path this PR adds;
    # every rollout re-quantizes the fresh version off-rotation (warm).
    dtype = "int8"
    agent = Agent(
        ImpalaNet(num_actions=6, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((obs_dim,), np.float32)
    )
    rng = np.random.default_rng(0)
    obs_pool = rng.normal(size=(64, obs_dim)).astype(np.float32)
    example = np.zeros((obs_dim,), np.float32)

    def make_fleet(replicas: int):
        store = ParamStore()
        store.publish(0, params)
        fleet = ServingFleet(
            agent=agent,
            store=store,
            example_obs=example,
            replicas=replicas,
            version=0,
            max_clients=clients + 2,
            max_batch=8,
            max_wait_s=1e-3,
            dtype=dtype,
            telemetry=Registry(),
        ).start()
        # Warm every replica's padded wave shape so jit compile never
        # lands inside a measured window (least-loaded routing would
        # send all sequential warmup traffic to r0 otherwise).
        for rep in fleet.replicas():
            c = InProcessClient(rep.server, greedy=True)
            c.act(obs_pool[0], True)
            c.close()
        return fleet, store

    def closed_loop_capacity(fleet) -> float:
        """Max sustained actions/s: every client re-submits the moment
        its answer lands (the ceiling an open-loop stream saturates)."""
        from torched_impala_tpu.serving import FleetClient

        stop = time.perf_counter() + calib_s
        counts = [0] * clients

        def drive(w: int) -> None:
            c = FleetClient(fleet, greedy=True, client_id=w)
            try:
                while time.perf_counter() < stop:
                    c.act(obs_pool[w % len(obs_pool)], True)
                    counts[w] += 1
            finally:
                c.close()

        threads = [
            threading.Thread(target=drive, args=(w,), daemon=True)
            for w in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / (time.perf_counter() - t0)

    def measure_arm(replicas: int, shape: TrafficShape):
        """One arm: open-loop load + a rollout driver re-deploying a
        freshly published version every `deploy_every_s`, and a
        chaos-harness server kill armed at the midpoint arrival.
        Returns (LoadReport, rollouts_completed, rollout_error)."""
        fleet, store = make_fleet(replicas)
        stop_evt = threading.Event()
        rollouts = [0]
        rollout_err = [None]
        mid = int(shape.rate_rps * shape.duration_s / 2)
        armed = [False]

        def arm_kill(i: int) -> None:
            # One-shot: the chaos fault fires on the next wave any
            # replica runs after the midpoint arrival is claimed.
            if i >= mid and not armed[0]:
                armed[0] = True
                injector = ChaosInjector(
                    ChaosPlan(
                        [Fault(kind="kill_server_mid_wave", at=1)]
                    ),
                    telemetry=Registry(),
                )
                injector.install(fleets=[fleet])

        def deployer() -> None:
            version = 1
            while not stop_evt.wait(deploy_every_s):
                try:
                    store.publish(version, params)
                    fleet.rollout(version, timeout_s=15.0)
                    rollouts[0] += 1
                    version += 1
                except Exception as e:  # pragma: no cover - bench alarm
                    rollout_err[0] = f"{type(e).__name__}: {e}"
                    return
        deploy_thread = threading.Thread(target=deployer, daemon=True)
        try:
            deploy_thread.start()
            report = run_load(
                fleet=fleet,
                shape=shape,
                slo_ms=slo_ms,
                example_obs=example,
                obs_pool=obs_pool,
                clients=clients,
                seed=2,
                on_arrival=arm_kill,
            )
        finally:
            stop_evt.set()
            deploy_thread.join(timeout=30.0)
            fleet.close()
        return report, rollouts[0], rollout_err[0]

    # The same gate run.py enforces: int8 may only serve if greedy
    # actions match f32 on the probe batch.
    parity_ok, parity_mismatches = greedy_action_parity(
        agent, params, obs_pool[:16], dtype=dtype
    )
    if not parity_ok:
        raise RuntimeError(
            f"{dtype} parity gate failed ({parity_mismatches} probe "
            "actions differ from f32) — refusing to bench a policy "
            "the serving tier would refuse to serve"
        )

    calib_fleet, _ = make_fleet(1)
    try:
        capacity_rps = closed_loop_capacity(calib_fleet)
    finally:
        calib_fleet.close()
    offered_rps = min(max(0.15 * capacity_rps, 300.0), 4000.0)
    shape = TrafficShape(
        kind="poisson", rate_rps=offered_rps, duration_s=dur_s
    )
    rep_single, rollouts_single, roll_err_single = measure_arm(1, shape)
    rep_fleet, rollouts_fleet, roll_err_fleet = measure_arm(2, shape)

    # Failover: comfortable rate plus slow-client/disconnect chaos
    # riders, one server killed mid-wave by the chaos harness. The
    # router must absorb it — mark the replica dead, retry its
    # in-flight requests exactly once on the survivor, and finish the
    # window with zero failed requests.
    failover_fleet, _ = make_fleet(2)
    try:
        injector = ChaosInjector(
            ChaosPlan([Fault(kind="kill_server_mid_wave", at=10)]),
            telemetry=Registry(),
        )
        injector.install(fleets=[failover_fleet])
        rep_failover = run_load(
            fleet=failover_fleet,
            shape=TrafficShape(
                kind="poisson",
                rate_rps=max(0.1 * capacity_rps, 30.0),
                duration_s=dur_s,
            ),
            slo_ms=slo_ms,
            example_obs=example,
            obs_pool=obs_pool,
            clients=clients,
            seed=3,
            disconnect_frac=0.02,
            slow_frac=0.02,
        )
        dead = [
            r.name
            for r in failover_fleet.replicas()
            if r.state == DEAD
        ]
        faults_fired = len(injector.fired)
    finally:
        failover_fleet.close()

    ratio = round(
        rep_fleet.goodput_rps / max(rep_single.goodput_rps, 1e-9), 2
    )
    out = {
        "clients": clients,
        "slo_ms": slo_ms,
        "dtype": dtype,
        "int8_parity": parity_ok,
        "int8_parity_mismatches": parity_mismatches,
        "capacity_rps": round(capacity_rps, 1),
        "offered_rps": round(offered_rps, 1),
        "deploy_every_s": deploy_every_s,
        "single": rep_single.summary(),
        "fleet": rep_fleet.summary(),
        "rollouts_single": rollouts_single,
        "rollouts_fleet": rollouts_fleet,
        "rollout_error_single": roll_err_single,
        "rollout_error_fleet": roll_err_fleet,
        "fleet_goodput_ratio": ratio,
        "serving_p99_ms": round(rep_fleet.p99_ms, 2),
        "serving_goodput_rps": round(rep_fleet.goodput_rps, 1),
        "failover": rep_failover.summary(),
        "failover_dead": dead,
        "failover_faults_fired": faults_fired,
    }
    log(
        f"bench: loadgen: fleet goodput {ratio}x single at "
        f"{out['offered_rps']} rps offered / {slo_ms}ms SLO under "
        f"rollouts every {deploy_every_s}s "
        f"({out['serving_goodput_rps']} vs "
        f"{rep_single.goodput_rps:.1f} rps; p99 fleet "
        f"{out['serving_p99_ms']}ms vs single "
        f"{rep_single.p99_ms:.1f}ms; rollouts "
        f"{rollouts_fleet}/{rollouts_single}, failed "
        f"{rep_fleet.failed}/{rep_single.failed}); failover: "
        f"failed={rep_failover.failed} retried={rep_failover.retried} "
        f"dead={dead}"
    )
    _history_append(
        "loadgen",
        {
            "fleet_goodput_ratio": ratio,
            "serving_goodput_rps": out["serving_goodput_rps"],
        },
        tiny=tiny,
    )
    _history_append(
        "loadgen",
        {"serving_p99_ms": out["serving_p99_ms"]},
        tiny=tiny,
        direction="lower",
    )
    return out


def run_bench_control(jax, tiny: bool = False) -> dict:
    """Closed-loop control plane (ISSUE 12 acceptance): controller-on
    must be no worse than the static defaults on the two standing
    scenarios the controller was built for, and the ratios land in
    BENCH_HISTORY.jsonl so perfgate pins them.

    Scenario 1 — standing stragglers (env pool): the async ready-set
    pool under 10% straggler injection, static ready_fraction=0.5 (the
    historical default) vs ready_fraction="auto" (the control-plane
    TargetMapPolicy tuner on the pool's own straggler EWMA). The auto
    arm gets an adaptation warmup first — the tuner retunes every
    AUTO_FRACTION_INTERVAL observed worker steps — then both arms are
    timed on the identical workload.

    Scenario 2 — serving burst: small bursts (4 clients) against a
    server whose coalescing window is generous (under-full waves always
    pay the whole window). The static arm keeps the configured window;
    the controller arm runs build_serving_control's SloPolicy against
    the request-wait p99 SLO, driven deterministically with
    ``loop.tick(now=...)`` between bursts (no thread, no sleeps). The
    controller shrinks the window/wave cap, so bursts stop paying the
    full wait.
    """
    import numpy as np

    from torched_impala_tpu import configs
    from torched_impala_tpu.control import build_serving_control
    from torched_impala_tpu.envs.fake import StragglerFactory
    from torched_impala_tpu.models import Agent, ImpalaNet, MLPTorso
    from torched_impala_tpu.runtime.env_pool import ProcessEnvPool
    from torched_impala_tpu.runtime.param_store import ParamStore
    from torched_impala_tpu.serving import (
        InProcessClient,
        PolicyServer,
        VersionRegistry,
    )
    from torched_impala_tpu.telemetry import FlightRecorder, Registry

    # ---- scenario 1: standing stragglers in the env pool -------------
    if tiny:
        W, E, T, unrolls, warmup_unrolls = 4, 2, 10, 3, 3
        straggler_delay_s = 0.025
    else:
        W, E, T, unrolls, warmup_unrolls = 8, 4, 20, 3, 4
        straggler_delay_s = 0.05
    base_delay_s, prob = 2e-3, 0.1
    obs_dim = 8
    inner = configs.make_env_factory(
        configs.ExperimentConfig(
            name="bench_control_pool",
            env_family="cartpole",
            obs_shape=(obs_dim,),
            num_actions=4,
        ),
        fake=True,
    )
    agent = Agent(
        ImpalaNet(num_actions=4, torso=MLPTorso(hidden_sizes=(64,)))
    )
    params = agent.init_params(
        jax.random.key(0), np.zeros((obs_dim,), np.float32)
    )
    store = ParamStore()
    store.publish(0, params)
    try:
        device = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        device = None
    from torched_impala_tpu.runtime.vector_actor import VectorActor

    def measure_pool(ready_fraction):
        factory = StragglerFactory(
            inner,
            base_delay_s=base_delay_s,
            straggler_delay_s=straggler_delay_s,
            straggler_prob=prob,
        )
        pool = ProcessEnvPool(
            env_factory=factory,
            num_workers=W,
            envs_per_worker=E,
            obs_shape=(obs_dim,),
            obs_dtype=np.float32,
            mode="async",
            ready_fraction=ready_fraction,
        )
        try:
            actor = VectorActor(
                actor_id=0,
                envs=pool,
                agent=agent,
                param_store=store,
                enqueue=lambda t: None,
                unroll_length=T,
                seed=0,
                device=device,
            )
            # Warmup compiles the wave shapes; in auto mode it is ALSO
            # the adaptation window the tuner converges inside.
            n_warm = warmup_unrolls if ready_fraction == "auto" else 1
            for _ in range(n_warm):
                actor.unroll_and_push()
            t0 = time.perf_counter()
            for _ in range(unrolls):
                actor.unroll_and_push()
            dt = time.perf_counter() - t0
            return (
                unrolls * T * pool.num_envs / dt,
                pool.ready_fraction,
            )
        finally:
            pool.close()

    static_sps, _ = measure_pool(0.5)
    auto_sps, tuned_fraction = measure_pool("auto")
    straggler = {
        "pool": f"{W}x{E} envs, T={T}, stragglers {prob:.0%}",
        "static_env_steps_per_sec": round(static_sps, 1),
        "auto_env_steps_per_sec": round(auto_sps, 1),
        "tuned_ready_fraction": round(float(tuned_fraction), 3),
        "controller_vs_static": round(auto_sps / static_sps, 3),
    }
    log(f"bench: control straggler: {straggler}")

    # ---- scenario 2: serving burst vs the coalescing window ----------
    burst, cap = 4, 16
    wait0_s = 0.010 if tiny else 0.025
    slo_ms = 2.0
    rounds = 12 if tiny else 40

    def measure_serving(controlled: bool):
        reg = Registry()
        s_store = ParamStore()
        s_store.publish(0, params)
        registry = VersionRegistry.serving_latest(s_store, telemetry=reg)
        server = PolicyServer(
            agent=agent,
            registry=registry,
            example_obs=np.zeros((obs_dim,), np.float32),
            max_clients=cap,
            max_batch=cap,
            max_wait_s=wait0_s,
            telemetry=reg,
        ).start()
        loop = None
        if controlled:
            loop = build_serving_control(
                server=server,
                slo_ms=slo_ms,
                telemetry=reg,
                tracer=FlightRecorder(capacity=256),
            )
        try:
            clients = [
                InProcessClient(server, greedy=True)
                for _ in range(burst)
            ]
            rng = np.random.default_rng(0)
            obs = rng.normal(size=(burst, obs_dim)).astype(np.float32)

            def round_trip(first):
                cells = [
                    c.act_async(obs[i], first)
                    for i, c in enumerate(clients)
                ]
                for cell in cells:
                    cell.result(timeout=120.0)

            round_trip(True)  # warmup: compiles the wave shape
            t0 = time.perf_counter()
            for r in range(rounds):
                round_trip(False)
                if loop is not None:
                    # Synthetic clock strides past the policy cooldown
                    # so every burst's evidence can move the knobs.
                    loop.tick(now=10.0 * (r + 1))
            dt = time.perf_counter() - t0
            for c in clients:
                c.close()
        finally:
            server.close()
        snap = reg.snapshot()
        return {
            "bursts_per_sec": round(rounds / dt, 2),
            "request_wait_ms_p99": round(
                float(snap["telemetry/serving/request_wait_ms_p99"]), 3
            ),
            "final_max_wait_ms": round(server.max_wait_s * 1e3, 3),
            "final_max_batch": int(server.max_batch),
            "decisions": int(
                snap.get("telemetry/control/decision_total", 0)
            ),
        }

    static_serving = measure_serving(controlled=False)
    controlled_serving = measure_serving(controlled=True)
    serving = {
        "burst": burst,
        "rounds": rounds,
        "configured_max_wait_ms": wait0_s * 1e3,
        "slo_ms": slo_ms,
        "static": static_serving,
        "controlled": controlled_serving,
        "controller_vs_static": round(
            controlled_serving["bursts_per_sec"]
            / max(static_serving["bursts_per_sec"], 1e-9),
            3,
        ),
    }
    log(f"bench: control serving: {serving}")

    out = {"straggler": straggler, "serving": serving}
    _history_append(
        "control",
        {
            "straggler_controller_vs_static": straggler[
                "controller_vs_static"
            ],
            "serving_controller_vs_static": serving[
                "controller_vs_static"
            ],
        },
        tiny=tiny,
    )
    return out


def run_vtrace_kernel_compare(jax) -> dict:
    """Compiled Pallas V-trace vs lax.scan on the real chip: equivalence +
    timing at Pong (T=20,B=256) and DMLab (T=100,B=32) shapes (VERDICT r1
    item 5). Returns per-shape microsecond timings."""
    import jax.numpy as jnp
    import numpy as np

    from torched_impala_tpu.ops.vtrace import vtrace_scan
    from torched_impala_tpu.ops.vtrace_pallas import vtrace_pallas

    out = {}
    rng = np.random.default_rng(0)
    for T, B in ((20, 256), (100, 32)):
        kwargs = dict(
            log_rhos=jnp.asarray(
                rng.normal(size=(T, B)) * 0.4, jnp.float32
            ),
            discounts=jnp.asarray(
                0.99 * (rng.uniform(size=(T, B)) > 0.02), jnp.float32
            ),
            rewards=jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
            values=jnp.asarray(rng.normal(size=(T, B)), jnp.float32),
            bootstrap_value=jnp.asarray(
                rng.normal(size=(B,)), jnp.float32
            ),
        )
        kwargs = jax.device_put(kwargs)
        scan_jit = jax.jit(lambda **kw: vtrace_scan(**kw))
        ref = scan_jit(**kwargs)
        res = vtrace_pallas(**kwargs, interpret=False)  # compiled Mosaic
        np.testing.assert_allclose(
            np.asarray(res.vs), np.asarray(ref.vs), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(res.pg_advantages),
            np.asarray(ref.pg_advantages),
            rtol=1e-5,
            atol=1e-5,
        )

        def bench_fn(fn, iters=200):
            jax.block_until_ready(fn(**kwargs).vs)
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(**kwargs)
            jax.block_until_ready(r.vs)
            return (time.perf_counter() - t0) / iters * 1e6

        scan_us = bench_fn(scan_jit)
        pallas_us = bench_fn(
            lambda **kw: vtrace_pallas(**kw, interpret=False)
        )
        out[f"T{T}_B{B}"] = {
            "scan_us": round(scan_us, 1),
            "pallas_us": round(pallas_us, 1),
            "pallas_speedup": round(scan_us / pallas_us, 2),
        }
        log(f"bench: vtrace T={T} B={B}: {out[f'T{T}_B{B}']}")
    return out


def run_attention_kernel_compare(jax) -> dict:
    """Fused Pallas attention vs the einsum dense path on the real chip, at
    the transformer core's actual shapes (pong_transformer preset: H=4,
    dh=64, W=128; learner re-forwards T = unroll+1 = 21). Checks compiled
    equivalence, then times forward and forward+backward (the custom-VJP
    Pallas recompute-backward kernel vs XLA's einsum backward)."""
    import jax.numpy as jnp
    import numpy as np

    from torched_impala_tpu.ops import attention_pallas as ap

    out = {}
    rng = np.random.default_rng(0)
    # Preset shapes (W=128 cache) + a long-context dense causal shape
    # (T=S=1024) where the einsum path materializes the [B, H, T, S]
    # logits/probs in HBM and the flash kernel's O(tile) residency should
    # pay off.
    for B, T, H, dh, W in (
        (32, 21, 4, 64, 128),
        (8, 101, 4, 64, 128),
        (8, 1024, 4, 64, 0),
    ):
        S = W + T
        q = jnp.asarray(rng.normal(size=(B, T, H, dh)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, dh)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, dh)), jnp.float32)
        seg_q = jnp.asarray(
            np.cumsum(rng.uniform(size=(B, T)) < 0.1, axis=1), jnp.int32
        )
        seg_ctx = jnp.concatenate(
            [
                jnp.asarray(
                    rng.integers(-1, 2, size=(B, W)).astype(np.int32)
                ),
                seg_q,
            ],
            axis=1,
        )
        q, k, v, seg_q, seg_ctx = jax.device_put((q, k, v, seg_q, seg_ctx))

        def einsum_ref(q, k, v):
            vis = ap._visibility(seg_q, seg_ctx, T, S, W)
            logits = jnp.einsum("bthd,bshd->bhts", q, k) / jnp.sqrt(
                float(dh)
            )
            logits = jnp.where(vis[:, None, :, :], logits, ap.NEG_INF)
            return jnp.einsum(
                "bhts,bshd->bthd", jax.nn.softmax(logits, axis=-1), v
            )

        pallas_fwd = jax.jit(
            lambda q, k, v: ap.windowed_attention(
                q, k, v, seg_q, seg_ctx, W, False
            )
        )
        einsum_fwd = jax.jit(einsum_ref)
        # Loose compiled-equivalence guard only: BOTH paths run at the
        # backend's default matmul precision (bf16 passes on the MXU), so
        # they differ from each other by bf16 rounding (~1e-2 on O(1)
        # outputs). Strict parity at `highest` precision is pinned in
        # tests/test_attention_pallas.py; this assert just catches a
        # wrong-mask/wrong-shape regression before timing garbage.
        np.testing.assert_allclose(
            np.asarray(pallas_fwd(q, k, v)),
            np.asarray(einsum_fwd(q, k, v)),
            rtol=2e-2,
            atol=2e-2,
        )
        pallas_bwd = jax.jit(
            jax.grad(lambda q, k, v: jnp.sum(jnp.sin(
                ap.windowed_attention(q, k, v, seg_q, seg_ctx, W, False)
            )), argnums=(0, 1, 2))
        )
        einsum_bwd = jax.jit(
            jax.grad(
                lambda q, k, v: jnp.sum(jnp.sin(einsum_ref(q, k, v))),
                argnums=(0, 1, 2),
            )
        )

        def bench_us(fn, iters=100):
            jax.block_until_ready(fn(q, k, v))
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(q, k, v)
            jax.block_until_ready(r)
            return (time.perf_counter() - t0) / iters * 1e6

        key = f"B{B}_T{T}"
        out[key] = {
            "fwd_einsum_us": round(bench_us(einsum_fwd), 1),
            "fwd_pallas_us": round(bench_us(pallas_fwd), 1),
            "fwdbwd_einsum_us": round(bench_us(einsum_bwd), 1),
            "fwdbwd_pallas_us": round(bench_us(pallas_bwd), 1),
        }
        out[key]["fwd_speedup"] = round(
            out[key]["fwd_einsum_us"] / out[key]["fwd_pallas_us"], 2
        )
        log(f"bench: attention {key}: {out[key]}")
    return out


def run_stack_reuse_compare() -> dict:
    """Fresh-allocation vs ring-reuse batch stacking at Atari shapes
    (VERDICT r3 item 5's resolution: the native C++ batcher lost to numpy
    in every measurement for two rounds and was retired; the REAL feed-
    path win is buffer reuse — fresh np.stack pays page faults +
    first-touch zeroing on every large output, reuse doesn't). Host-side
    only, chip-independent; LearnerConfig.stack_buffer_reuse is the
    product flag."""
    import numpy as np

    from torched_impala_tpu.runtime.learner import (
        alloc_stack_buffers,
        stack_trajectories,
    )
    from torched_impala_tpu.runtime.types import Trajectory

    out = {}
    rng = np.random.default_rng(0)
    for T, B in ((20, 32), (20, 256)):
        trajs = [
            Trajectory(
                obs=rng.integers(
                    0, 256, size=(T + 1, 84, 84, 4), dtype=np.uint8
                ),
                first=np.zeros((T + 1,), np.bool_),
                actions=np.zeros((T,), np.int32),
                behaviour_logits=np.zeros((T, 6), np.float32),
                rewards=np.zeros((T,), np.float32),
                cont=np.ones((T,), np.float32),
                agent_state=(),
                actor_id=0,
                param_version=0,
                task=0,
            )
            for _ in range(B)
        ]
        mb = (T + 1) * B * 84 * 84 * 4 / 1e6
        ring = [alloc_stack_buffers(trajs) for _ in range(2)]
        # The fresh arm must model the REAL batcher's retention: queued +
        # in-transfer batches stay alive, so malloc cannot just recycle
        # the previous output (an immediately-freed fresh arm understates
        # the allocation cost by ~3x at these sizes).
        held = []

        def fresh(i):
            held.append(stack_trajectories(trajs))
            if len(held) > 3:
                held.pop(0)

        def timeit(fn, iters=30):
            fn(0)  # warm
            t0 = time.perf_counter()
            for i in range(iters):
                fn(i)
            return (time.perf_counter() - t0) / iters * 1e3

        fresh_ms = timeit(fresh)
        reuse_ms = timeit(
            lambda i: stack_trajectories(trajs, out=ring[i % 2])
        )
        # Ring stacking + an explicit copy of the stacked obs into a
        # second preallocated buffer — a stand-in for a production
        # host's copying H2D (pinned-staging memcpy; the DMA itself is
        # hardware). The integrated CPU drain can't show this arm
        # because jax CPU device_put aliases (ring auto-disables); this
        # is the honest per-core estimate of the path a real TPU host
        # runs: queue -> ring-stack -> copying transfer.
        staging = [np.empty_like(ring[0].obs) for _ in range(2)]

        def reuse_plus_copy(i):
            stack_trajectories(trajs, out=ring[i % 2])
            np.copyto(staging[i % 2], ring[i % 2].obs)

        reuse_h2d_ms = timeit(reuse_plus_copy)
        key = f"T{T}_B{B}_{mb:.0f}MB"
        out[key] = {
            "fresh_ms": round(fresh_ms, 2),
            "reuse_ms": round(reuse_ms, 2),
            "reuse_speedup": round(fresh_ms / reuse_ms, 2),
            "reuse_GBps": round(mb / reuse_ms, 2),
            "reuse_plus_sim_h2d_ms": round(reuse_h2d_ms, 2),
            "reuse_plus_sim_h2d_GBps": round(mb / reuse_h2d_ms, 2),
        }
        log(f"bench: stack reuse {key}: {out[key]}")
    return out


def run_e2e_components(jax) -> dict:
    """Per-component rate probes behind the integrated e2e number
    (VERDICT r4 weak #2: 'decompose the gap, not just one number').

    Every stage of the host-actor pipeline runs SERIALIZED on this box's
    one core, so the integrated ceiling is the harmonic composition of
    the component rates measured here: per frame,
        1/e2e ~ 1/env_step + 1/policy_step + 1/stack + 1/(H2D+step).
    The keys give each component's standalone frames/s on one core; the
    `predicted_*` keys compose them; production sizing falls out (e.g.
    env stepping at N f/s/core => 62.5k f/s/chip needs 62.5k/N env
    cores per chip on a real multi-core host).
    """
    import numpy as np

    from torched_impala_tpu import configs
    from torched_impala_tpu.envs.fake import FakeAtariEnv

    out = {}
    cfg = configs.REGISTRY["pong"]

    # 1) Raw env stepping (the reference architecture's per-core unit of
    # scale): fake Atari — real ALE is 3-8k f/s/core, the fake is pure
    # numpy obs generation, so this is the HARNESS ceiling, not ALE's.
    env = FakeAtariEnv()
    env.reset(seed=0)
    n = 3000
    t0 = time.perf_counter()
    for i in range(n):
        _, _, term, trunc, _ = env.step(i % 6)
        if term or trunc:
            env.reset()
    out["env_step_only_fps_1core"] = round(n / (time.perf_counter() - t0), 1)

    # 2) Actor-side policy inference at E envs per dispatch on the HOST
    # CPU device (what actor_device='cpu' runs): batching amortizes
    # dispatch — the E=1 vs E=16 spread is the vectorization win.
    import jax.numpy as jnp

    agent = configs.make_agent(cfg)
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except Exception:
        cpu = jax.devices()[0]
    with jax.default_device(cpu):
        params = jax.device_put(
            agent.init_params(
                jax.random.key(0), jnp.zeros((84, 84, 4), jnp.uint8)
            ),
            cpu,
        )
        for E in (1, 16):
            obs = np.zeros((E, 84, 84, 4), np.uint8)
            first = np.zeros((E,), np.bool_)
            state = jax.device_put(agent.initial_state(E), cpu)
            rng = jax.random.key(1)

            def step(params, obs, first, state, rng):
                rng, key = jax.random.split(rng)
                agent_out = agent.step(
                    params, key, jnp.asarray(obs), jnp.asarray(first), state
                )
                return agent_out.action, agent_out.state, rng

            jstep = jax.jit(step)
            a, state, rng = jstep(params, obs, first, state, rng)
            jax.block_until_ready(a)
            iters = 120
            t0 = time.perf_counter()
            for _ in range(iters):
                a, state, rng = jstep(params, obs, first, state, rng)
            jax.block_until_ready(a)
            dt = time.perf_counter() - t0
            out[f"policy_step_fps_E{E}_1core"] = round(E * iters / dt, 1)

    # 3) Stacking + 4) H2D + 5) learner compute live in their own
    # sections (stack_reuse_compare, feeder_saturation, the headline);
    # compose the host-side chain here so the JSON carries the derived
    # ceiling next to the inputs.
    env_fps = out["env_step_only_fps_1core"]
    pol_fps = out["policy_step_fps_E16_1core"]
    # Ring stacking at the headline shape ~ 4.4 GB/s (stack_reuse
    # section) = ~150k f/s at 29.7 KB/frame; on one serialized core the
    # env+policy terms dominate by 20-50x, so the two-term compose is
    # the honest predictor (stacking/H2D add <2%). The integrated e2e_*
    # windows can read ABOVE this: the learner's steady-state window
    # partially drains queue backlog built during its ~30 s compile, so
    # treat e2e_* as an upper read and this as the sustained floor.
    out["predicted_serial_1core_fps"] = round(
        1.0 / (1.0 / env_fps + 1.0 / pol_fps), 1
    )
    out["bottleneck_1core"] = (
        "actor-side policy inference (f32/bf16 CNN fwd on one CPU core)"
        if pol_fps < env_fps
        else "env stepping"
    )
    out["production_note"] = (
        "one production chip at 62.5k f/s needs "
        f"ceil(62500/{env_fps:.0f})={int(np.ceil(62500 / env_fps))} "
        "fake-env cores (real ALE ~3-8k f/s/core => 8-21 cores) + "
        f"62500/{pol_fps:.0f}={62500 / pol_fps:.1f} host-CPU inference "
        "cores — i.e. host inference cannot feed a chip; production "
        "actors put inference on the accelerator (reference design) or "
        "an inference-dedicated slice, while env stepping stays on "
        "host cores; this box has 1 core for all of it"
    )
    log(f"bench: e2e components: {out}")
    return out


def run_e2e(jax, tpu_ok: bool, actor_mode: str) -> dict:
    """Whole-pipeline throughput: fake Atari envs -> actors -> batcher ->
    H2D -> learner (VERDICT r1 item 4 — the number the 1M-frames/s target
    actually constrains, SURVEY.md §8 hard part 1). Returns
    env-frames/s consumed by the learner plus batch_wait_frac (fraction of
    learner wall-time spent waiting on the batcher: >0 means host-bound).

    The companion `e2e_components` section decomposes the gap between
    this number and the learner-compute headline into per-stage rates."""
    import numpy as np
    import optax

    from torched_impala_tpu import configs
    from torched_impala_tpu.ops import ImpalaLossConfig
    from torched_impala_tpu.runtime.learner import LearnerConfig
    from torched_impala_tpu.runtime.loop import train

    if tpu_ok:
        # Sized for this 1-core build box (measured 2026-07-29: 60 steps at
        # 8x8 actors took ~16min/mode, host-bound at ~50-90 f/s): enough
        # steps for a steady-state window, small enough to finish both modes
        # inside the wall-clock alarm. The number is host-bound context, not
        # the headline metric.
        # 1 actor x 16 vectorized envs edges out 4x4 on this 1-core box
        # (r5 10-step probes: 519 vs 489 f/s): one policy dispatch
        # serves 16 envs (e2e_components' E=1 vs E=16 spread is 27.8 ->
        # 260 f/s) and thread context switching drops.
        T, B, steps = 20, 16, 24
        num_actors, envs_per_actor = 1, 16
    else:
        T, B, steps = 10, 4, 6
        num_actors, envs_per_actor = 2, 4
    cfg = configs.REGISTRY["pong"]
    agent = configs.make_agent(cfg)
    env_factory = configs.make_env_factory(cfg, fake=True)
    log(
        f"bench: e2e {actor_mode} T={T} B={B} steps={steps} "
        f"actors={num_actors}x{envs_per_actor}"
    )
    t0 = time.perf_counter()
    result = train(
        agent=agent,
        env_factory=env_factory,
        example_obs=configs.example_obs(cfg),
        num_actors=num_actors,
        learner_config=LearnerConfig(
            batch_size=B,
            unroll_length=T,
            loss=ImpalaLossConfig(reduction="sum"),
        ),
        optimizer=optax.rmsprop(6e-4, decay=0.99, eps=1e-7),
        total_steps=steps,
        log_every=max(1, steps // 3),
        envs_per_actor=envs_per_actor,
        actor_mode=actor_mode,
    )
    dt = time.perf_counter() - t0
    out = {
        # Steady-state: the learner's last log window (excludes compile).
        "env_frames_per_sec": round(
            float(result.final_logs.get("frames_per_sec", float("nan"))), 1
        ),
        "env_frames_per_sec_incl_compile": round(
            result.num_frames / dt, 1
        ),
        "batch_wait_frac": round(
            float(result.final_logs.get("batch_wait_frac", float("nan"))), 4
        ),
        "learner_steps": result.learner.num_steps,
        "wall_seconds": round(dt, 2),
        "actors": f"{num_actors}x{envs_per_actor}",
    }
    log(f"bench: e2e {actor_mode}: {out}")
    return out


if __name__ == "__main__":
    _args = parse_args()
    try:
        # Hard wall-clock bound: if the tunnel wedges MID-run (probe passed
        # but a later dispatch hangs), fail into the JSON error path instead
        # of hanging the driver.
        import signal

        def _alarm(signum, frame):
            raise TimeoutError("bench wall-clock limit hit (wedged tunnel?)")

        signal.signal(signal.SIGALRM, _alarm)
        # Full: 2700s — the section list grew round 3 (remat, feeder,
        # attention, anakin sweep); still inside tunnel_watch.sh's 3000s
        # hard timeout so the watcher never SIGKILLs a live bench.
        # Fast: 300s — the mode exists to bank numbers inside a short
        # tunnel-heal window; the alarm fires into the partial-JSON path,
        # which has already persisted every completed section.
        # The measurement alarm arms only in the POST-resolve process (the
        # pre-resolve one execve()s away, discarding its alarm), so the
        # probe ladder — already bounded at 150s per candidate subprocess —
        # never eats the fast budget; the pre-resolve process gets its own
        # generous ladder bound instead.
        if _RESOLVED_MARKER in os.environ:
            signal.alarm(300 if _args.fast else 2700)
        else:
            signal.alarm(1200)
        main(_args)
    except Exception as e:  # still emit ONE parseable JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "learner_frames_per_sec_per_chip_pong",
                    "value": 0.0,
                    "unit": "frames/s/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}"[:500],
                }
            )
        )
        sys.exit(1)
