#!/bin/bash
# Watch for axon tunnel recovery; run bench.py the moment it heals.
cd /root/repo
for i in $(seq 1 40); do
  if timeout 150 python -c "import jax; print(jax.devices())" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) tunnel ALIVE (iter $i); running bench" >> /tmp/tunnel_watch.log
    timeout 3000 python bench.py > /root/repo/BENCH_watch.json 2> /tmp/bench_watch.log
    echo "$(date +%H:%M:%S) bench rc=$? json=$(cat /root/repo/BENCH_watch.json | head -c 200)" >> /tmp/tunnel_watch.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) tunnel still wedged (iter $i)" >> /tmp/tunnel_watch.log
  sleep 600
done
echo "$(date +%H:%M:%S) gave up after 40 iters" >> /tmp/tunnel_watch.log
