#!/bin/bash
# Watch for axon tunnel recovery; capture + commit a fresh full bench the
# moment it heals (includes fused-dispatch and anakin sections).
cd /root/repo
for i in $(seq 1 60); do
  # ONE TPU client at a time: if a bench is already running (e.g. the
  # round driver's), skip this iteration entirely — even the probe is a
  # tunnel client.
  if pgrep -f "python bench.py" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) bench already running; skipping probe (iter $i)" >> /tmp/tunnel_watch.log
    sleep 600
    continue
  fi
  if timeout 150 python -c "import jax; print(jax.devices())" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) tunnel ALIVE (iter $i); running bench" >> /tmp/tunnel_watch.log
    timeout 3000 python bench.py > /root/repo/BENCH_watch.json 2> /tmp/bench_watch.log
    rc=$?
    echo "$(date +%H:%M:%S) bench rc=$rc json=$(head -c 200 /root/repo/BENCH_watch.json)" >> /tmp/tunnel_watch.log
    if [ $rc -eq 0 ] && grep -q '"backend": "tpu"' /root/repo/BENCH_watch.json; then
      cp /root/repo/BENCH_watch.json /root/repo/BENCH_live.json
      git add BENCH_live.json BENCH_watch.json traces/bench 2>/dev/null
      git commit -m "bench: fresh real-chip capture after tunnel recovery (fused + anakin sections)" -- BENCH_live.json BENCH_watch.json traces/bench >> /tmp/tunnel_watch.log 2>&1
      echo "$(date +%H:%M:%S) committed fresh TPU bench" >> /tmp/tunnel_watch.log
      exit 0
    fi
    echo "$(date +%H:%M:%S) bench did not reach TPU; continuing watch" >> /tmp/tunnel_watch.log
  else
    echo "$(date +%H:%M:%S) tunnel still wedged (iter $i)" >> /tmp/tunnel_watch.log
  fi
  sleep 600
done
echo "$(date +%H:%M:%S) gave up after 60 iters" >> /tmp/tunnel_watch.log
