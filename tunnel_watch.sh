#!/bin/bash
# Watch for axon tunnel recovery; bank numbers the moment it heals.
#
# Two-stage capture (VERDICT r3 item 1): a short tunnel-heal window must
# still produce on-chip numbers, so on probe success we run `bench.py
# --fast` FIRST (headline + fused + anakin_pixels locked configs, hard
# 300s alarm, partial JSON after every section) and commit it, and only
# then attempt the full-section run. The full run also writes per-section
# partial JSON, so even a mid-run re-wedge leaves committable sections.
cd /root/repo
for i in $(seq 1 60); do
  # ONE TPU client at a time: if a bench is already running (e.g. the
  # round driver's), skip this iteration entirely — even the probe is a
  # tunnel client.
  if pgrep -f "python bench.py" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) bench already running; skipping probe (iter $i)" >> /tmp/tunnel_watch.log
    sleep 600
    continue
  fi
  if timeout 150 python -c "import jax; print(jax.devices())" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) tunnel ALIVE (iter $i); running FAST bench" >> /tmp/tunnel_watch.log
    # Stale out-files from a previous iteration must never be committed as
    # fresh captures: a bench that dies before its first write leaves the
    # old file in place for the grep below.
    rm -f /root/repo/BENCH_fast.json
    timeout 420 python bench.py --fast --out /root/repo/BENCH_fast.json \
      > /tmp/bench_fast_line.json 2> /tmp/bench_fast.log
    rc=$?
    echo "$(date +%H:%M:%S) fast bench rc=$rc json=$(head -c 200 /root/repo/BENCH_fast.json 2>/dev/null)" >> /tmp/tunnel_watch.log
    if grep -q '"backend": "tpu"' /root/repo/BENCH_fast.json 2>/dev/null; then
      git add BENCH_fast.json 2>/dev/null
      git commit -m "bench: fast-mode real-chip capture (headline + fused + anakin_pixels)" -- BENCH_fast.json >> /tmp/tunnel_watch.log 2>&1
      echo "$(date +%H:%M:%S) committed fast TPU capture" >> /tmp/tunnel_watch.log
    fi
    echo "$(date +%H:%M:%S) running FULL bench" >> /tmp/tunnel_watch.log
    rm -f /root/repo/BENCH_watch.json
    timeout 3000 python bench.py --out /root/repo/BENCH_watch.json \
      > /tmp/bench_line.json 2> /tmp/bench_watch.log
    rc=$?
    echo "$(date +%H:%M:%S) full bench rc=$rc json=$(head -c 200 /root/repo/BENCH_watch.json 2>/dev/null)" >> /tmp/tunnel_watch.log
    if grep -q '"backend": "tpu"' /root/repo/BENCH_watch.json 2>/dev/null; then
      if [ $rc -eq 0 ] && grep -q '"partial": false' /root/repo/BENCH_watch.json; then
        cp /root/repo/BENCH_watch.json /root/repo/docs/evidence/BENCH_live.json
        git add -f docs/evidence/BENCH_live.json BENCH_watch.json traces/bench traces/anakin_pixels 2>/dev/null
        git commit -m "bench: fresh full-section real-chip capture after tunnel recovery" -- docs/evidence/BENCH_live.json BENCH_watch.json traces/bench traces/anakin_pixels >> /tmp/tunnel_watch.log 2>&1
        echo "$(date +%H:%M:%S) committed fresh full TPU bench" >> /tmp/tunnel_watch.log
        exit 0
      fi
      # Partial full run on TPU: bank whatever sections finished.
      git add BENCH_watch.json 2>/dev/null
      git commit -m "bench: partial real-chip capture (full run interrupted)" -- BENCH_watch.json >> /tmp/tunnel_watch.log 2>&1
      echo "$(date +%H:%M:%S) committed PARTIAL full-run capture (rc=$rc)" >> /tmp/tunnel_watch.log
    fi
    echo "$(date +%H:%M:%S) full bench did not complete on TPU; continuing watch" >> /tmp/tunnel_watch.log
  else
    echo "$(date +%H:%M:%S) tunnel still wedged (iter $i)" >> /tmp/tunnel_watch.log
  fi
  sleep 600
done
echo "$(date +%H:%M:%S) gave up after 60 iters" >> /tmp/tunnel_watch.log
